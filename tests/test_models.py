"""Model-family e2e tests — BERT/ERNIE (baseline config 3) and GPT
(config 4), SURVEY.md §4: every model family gets a train-step
convergence test and a semantics test."""

import pytest

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.tensor import Tensor
from paddle_tpu.distributed import collective
from paddle_tpu.distributed.runner import DistributedRunner

pytestmark = pytest.mark.slow


def _tiny_bert_cfg(Cls):
    return Cls(vocab_size=256, hidden_size=32, num_hidden_layers=2,
               num_attention_heads=4, intermediate_size=64,
               max_position_embeddings=64, type_vocab_size=2,
               hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


def test_bert_pretraining_loss_decreases():
    import jax
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   BertPretrainingCriterion)

    paddle.seed(0)
    cfg = _tiny_bert_cfg(BertConfig)
    net = BertForPretraining(cfg)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int64)
    mlm = ids.copy()
    mlm[:, ::3] = -100               # only every-3rd position is masked
    nsp = rng.randint(0, 2, (4,)).astype(np.int64)
    mesh = collective.build_mesh({})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt,
                               BertPretrainingCriterion(cfg.vocab_size),
                               mesh=mesh)
    losses = [float(runner.train_step([ids], [Tensor(mlm), Tensor(nsp)]))
              for _ in range(5)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_bert_attention_mask_blocks_padding():
    from paddle_tpu.models import BertConfig, BertModel

    paddle.seed(0)
    cfg = _tiny_bert_cfg(BertConfig)
    net = BertModel(cfg)
    net.eval()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, cfg.vocab_size, (1, 16)).astype(np.int64)
    mask = np.ones((1, 16), np.float32)
    mask[:, 8:] = 0.0                # second half is padding
    seq1, _ = net(Tensor(ids), attention_mask=Tensor(mask))
    ids2 = ids.copy()
    ids2[:, 8:] = rng.randint(0, cfg.vocab_size, (1, 8))  # change padding
    seq2, _ = net(Tensor(ids2), attention_mask=Tensor(mask))
    # unmasked positions must be unaffected by padding-token content
    np.testing.assert_allclose(np.asarray(seq1.numpy())[:, :8],
                               np.asarray(seq2.numpy())[:, :8],
                               rtol=1e-5, atol=1e-6)


def test_ernie_sequence_classification_finetune():
    from paddle_tpu.models import (ErnieConfig,
                                   ErnieForSequenceClassification)

    paddle.seed(0)
    cfg = _tiny_bert_cfg(ErnieConfig)
    net = ErnieForSequenceClassification(cfg, num_classes=3)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    rng = np.random.RandomState(2)
    ids = rng.randint(0, cfg.vocab_size, (8, 24)).astype(np.int64)
    labels = rng.randint(0, 3, (8,)).astype(np.int64)
    mesh = collective.build_mesh({})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                               mesh=mesh)
    losses = [float(runner.train_step([ids], [labels]))
              for _ in range(5)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_gpt_causality():
    """Changing a future token must not affect earlier logits."""
    from paddle_tpu.models import gpt_tiny, GPTForCausalLM

    paddle.seed(0)
    cfg = gpt_tiny(use_flash_attention=False)
    net = GPTForCausalLM(cfg)
    net.eval()
    rng = np.random.RandomState(3)
    ids = rng.randint(0, cfg.vocab_size, (1, 16)).astype(np.int64)
    out1 = np.asarray(net(Tensor(ids)).numpy())
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 1) % cfg.vocab_size
    out2 = np.asarray(net(Tensor(ids2)).numpy())
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1],
                               rtol=1e-5, atol=1e-6)
    assert not np.allclose(out1[:, -1], out2[:, -1])


def test_resnet18_train_step_with_bn_buffers():
    """Config-2 family: ResNet train step through the compiled runner —
    BN running stats must update through the step."""
    from paddle_tpu.vision.models import resnet18

    paddle.seed(0)
    net = resnet18(num_classes=10)
    opt = optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                             parameters=net.parameters())
    rng = np.random.RandomState(0)
    x = rng.rand(4, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (4,)).astype(np.int64)
    mesh = collective.build_mesh({})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                               mesh=mesh)
    bn_before = {n: np.asarray(b._value).copy()
                 for n, b in net.named_buffers()
                 if b is not None and "mean" in n}
    l1 = float(runner.train_step([x], [y]))
    l2 = float(runner.train_step([x], [y]))
    assert np.isfinite([l1, l2]).all()
    changed = any(
        not np.allclose(np.asarray(dict(net.named_buffers())[n]._value),
                        v)
        for n, v in bn_before.items())
    assert changed, "BatchNorm running stats did not update"


def test_vit_tiny_train_step():
    """Config-5 family: ViT train step converges."""
    from paddle_tpu.vision.models import VisionTransformer

    paddle.seed(0)
    net = VisionTransformer(img_size=32, patch_size=8, in_chans=3,
                            num_classes=5, embed_dim=32, depth=2,
                            num_heads=4, drop_rate=0.0,
                            attn_drop_rate=0.0)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    rng = np.random.RandomState(1)
    x = rng.rand(4, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 5, (4,)).astype(np.int64)
    mesh = collective.build_mesh({})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                               mesh=mesh)
    losses = [float(runner.train_step([x], [y])) for _ in range(5)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_ernie_finetune_on_imdb_via_hapi():
    """Config-3-class fine-tune loop: ErnieForSequenceClassification
    (tiny) + paddle.text.Imdb + Model.fit (the full user workflow:
    dataset -> DataLoader -> hapi -> compiled step)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer, text
    from paddle_tpu.models import BertConfig, BertForSequenceClassification

    paddle.seed(0)
    cfg = BertConfig(vocab_size=5147, hidden_size=32,
                     num_hidden_layers=2, num_attention_heads=2,
                     intermediate_size=64, max_position_embeddings=128,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    net = BertForSequenceClassification(cfg, num_classes=2)
    m = paddle.Model(net)
    m.prepare(optimizer.Adam(5e-3, parameters=m.parameters()),
              nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    import os
    os.environ["PADDLE_TPU_SYNTH_N"] = "128"
    try:
        ds = text.Imdb(mode="train", seq_len=32)
        hist = m.fit(ds, epochs=10, batch_size=32, verbose=0)
        ev = m.evaluate(text.Imdb(mode="test", seq_len=32),
                        batch_size=32, verbose=0)
    finally:
        os.environ["PADDLE_TPU_SYNTH_N"] = "512"
    # the synthetic corpus is separable by construction
    assert ev["acc"] > 0.9, ev


def test_vit_multi_resolution_bucketed_training():
    """Config 5's ViT dynamic-shape story: position embeddings
    interpolate per resolution bucket, one compiled program per bucket,
    and training decreases loss across MIXED-resolution steps."""
    import numpy as np
    from paddle_tpu import nn, optimizer
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.vision.models import VisionTransformer

    paddle.seed(0)
    rng = np.random.RandomState(0)
    net = VisionTransformer(img_size=32, patch_size=8, in_chans=3,
                            num_classes=4, embed_dim=64, depth=2,
                            num_heads=4)
    net.train()
    opt = optimizer.Adam(5e-3, parameters=net.parameters())
    lossf = nn.CrossEntropyLoss()
    # two resolution buckets: the build size (32 -> 4x4 patches) and a
    # larger eval-style size (48 -> 6x6 patches)
    batches = {}
    for size in (32, 48):
        x = rng.rand(4, 3, size, size).astype(np.float32)
        y = rng.randint(0, 4, (4,)).astype(np.int64)
        batches[size] = (x, y)
    first, last = {}, {}
    for step in range(40):
        size = (32, 48)[step % 2]
        x, y = batches[size]
        loss = lossf(net(Tensor(x)), Tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        lv = float(loss.numpy())
        first.setdefault(size, lv)
        last[size] = lv
    for size in (32, 48):
        assert last[size] < 0.6 * first[size], \
            f"bucket {size}: {first[size]} -> {last[size]}"


def test_vit_pos_embed_interpolation_identity_and_refusal():
    import numpy as np
    import pytest
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.vision.models import VisionTransformer

    paddle.seed(0)
    net = VisionTransformer(img_size=32, patch_size=8, in_chans=3,
                            num_classes=0, embed_dim=64, depth=1,
                            num_heads=4)
    net.eval()
    # same resolution: the exact table is used (identity)
    pe = net._pos_embed_for(16)
    assert pe is net.pos_embed
    # non-square patch count refuses loudly
    with pytest.raises(ValueError, match="non-square"):
        net._pos_embed_for(15)
    # different square resolution produces the right count
    pe = net._pos_embed_for(36)
    assert tuple(pe.shape) == (1, 37, 64)
