"""SLO-aware serving router tests (ISSUE 13 §Action loop): least-
loaded routing + QueueFull failover, admission shedding (state
transitions, the droppable ``router.shed`` chaos site), scale-up/down
hysteresis + cooldown with deterministic stub replicas, injected
``replica.spawn`` failure survival, the windowed-p99 histogram-diff
math, a real-LLMServer end-to-end routing pin, and the slow-marked
burst chaos e2e: a 10× Poisson burst must spawn a replica, shed the
excess, and recover p99 below the SLO knob — every decision visible
on ``/events`` and the registry.
"""

import itertools
import json
import math
import threading
import time
import types
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.resilience import FaultPlan, clear, install
from paddle_tpu.inference.serving import (
    LLMServer, Overloaded, QueueFull, ServingModelConfig,
    ServingRouter, extract_decode_params, reference_decode)
from paddle_tpu.inference.serving.router import (_delta_quantile,
                                                 _window_cum)
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.observability import events as obs_events
from paddle_tpu.observability import metrics as obs_metrics


@pytest.fixture(autouse=True)
def _clean():
    clear()
    obs_events._reset_for_tests()
    yield
    clear()
    obs_events._reset_for_tests()


# ---------------------------------------------------------------------------
# deterministic stub replicas: the exact surface the router reads
# ---------------------------------------------------------------------------
_stub_ids = itertools.count()


class _StubServer:
    """Mimics the LLMServer surface the router uses: ``submit``,
    ``close``, and the engine's host-only signals (queue depth,
    active count, the cumulative latency histogram child)."""

    def __init__(self):
        self._label = {"engine": f"stub{next(_stub_ids)}"}
        reg = obs_metrics.registry()
        h = reg.histogram("serving_latency_s", "request latency",
                          labels=self._label)
        self.engine = types.SimpleNamespace(
            scheduler=types.SimpleNamespace(queue_depth=0),
            active_count=0, _h_latency=h)
        self.queue_full = False
        self.submitted = []
        self.closed = False
        self.unregistered = False

    def set_load(self, queue=0, active=0):
        self.engine.scheduler.queue_depth = queue
        self.engine.active_count = active

    def observe_latency(self, *vals):
        for v in vals:
            self.engine._h_latency.observe(v)

    def submit(self, prompt_ids, max_tokens, stream_cb=None, **kw):
        if self.queue_full:
            raise QueueFull("stub queue full")
        self.submitted.append(list(prompt_ids))
        return Future()

    def close(self, unregister_metrics=False):
        self.closed = True
        if unregister_metrics:
            self.unregistered = True
            obs_metrics.registry().unregister("serving_latency_s",
                                              labels=self._label)


def _stub_router(n=1, factory_log=None, **kw):
    made = factory_log if factory_log is not None else []

    def factory():
        s = _StubServer()
        made.append(s)
        return s

    kw.setdefault("min_replicas", n)
    kw.setdefault("max_replicas", max(n, 2))
    kw.setdefault("decision_interval_s", 0)   # tests drive rounds
    kw.setdefault("cooldown_s", 0.0)
    return ServingRouter(factory, **kw), made


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------
def test_submit_routes_to_least_loaded_and_fails_over():
    router, made = _stub_router(n=2, max_replicas=2)
    try:
        a, b = made
        a.set_load(queue=3, active=2)
        b.set_load(queue=0, active=1)
        router.submit([1, 2], 4)
        assert b.submitted and not a.submitted
        # failover: the light replica refuses, the heavy one admits
        b.queue_full = True
        router.submit([3], 4)
        assert a.submitted
    finally:
        router.close()


def test_all_queues_full_sheds_with_counter():
    router, made = _stub_router(n=2, max_replicas=2)
    shed0 = router._c_shed.collect()
    try:
        for s in made:
            s.queue_full = True
        with pytest.raises(Overloaded):
            router.submit([1], 4)
        assert router._c_shed.collect() == shed0 + 1
        # Overloaded IS QueueFull: upstream backpressure handling
        # written against LLMServer covers the router unchanged
        with pytest.raises(QueueFull):
            router.submit([1], 4)
    finally:
        router.close()


def test_draining_replica_gets_no_admissions():
    router, made = _stub_router(n=2, max_replicas=2)
    try:
        victim = router._replicas[0]
        victim.draining = True
        router.submit([1], 4)
        assert not made[0].submitted and made[1].submitted
    finally:
        router.close()


def test_replica_count_validation():
    with pytest.raises(ValueError):
        _stub_router(n=0)
    with pytest.raises(ValueError):
        _stub_router(n=2, max_replicas=1)


# ---------------------------------------------------------------------------
# scaling policy (hysteresis, cooldown, chaos)
# ---------------------------------------------------------------------------
def test_scale_up_needs_consecutive_windows_then_cooldown():
    router, made = _stub_router(n=1, max_replicas=3, windows_up=3,
                                cooldown_s=60.0,
                                scale_up_queue_depth=4.0)
    ups0 = router._c_up.collect()
    try:
        made[0].set_load(queue=10)
        assert router.control_round()["decision"] == "hold"
        assert router.control_round()["decision"] == "hold"
        # third consecutive overloaded window spawns
        assert router.control_round()["decision"] == "scale_up"
        assert router.num_replicas == 2 and len(made) == 2
        assert router._c_up.collect() == ups0 + 1
        # still overloaded, but cooldown holds capacity; the overload
        # capacity can't absorb turns shedding ON instead
        made[1].set_load(queue=10)
        for _ in range(3):
            router.control_round()
        assert router.num_replicas == 2
        assert router.shedding
        kinds = [e["kind"] for e in obs_events.snapshot()]
        assert "scale_up" in kinds and "shed_on" in kinds
        # load drains: shedding turns back off, with the transition
        # on the ring
        for s in made:
            s.set_load(queue=0)
        router.control_round()
        assert not router.shedding
        assert obs_events.snapshot()[-1]["kind"] == "shed_off"
    finally:
        router.close()


def test_one_healthy_window_resets_the_up_streak():
    router, made = _stub_router(n=1, windows_up=2,
                                scale_up_queue_depth=4.0)
    try:
        made[0].set_load(queue=10)
        router.control_round()
        made[0].set_load(queue=0)      # healthy window in between
        router.control_round()
        made[0].set_load(queue=10)
        router.control_round()
        assert router.num_replicas == 1   # streak restarted at 1
    finally:
        router.close()


def test_injected_spawn_failure_survives_and_retries():
    """replica.spawn is chaos surface: an injected failure aborts ONE
    spawn (capacity unchanged, decision on the ring) and the next
    overloaded round retries."""
    router, made = _stub_router(n=1, max_replicas=2, windows_up=1,
                                scale_up_queue_depth=1.0)
    try:
        made[0].set_load(queue=10)
        # the injector counts from install time, so the scale-up
        # spawn is site call #1 here (init's spawn predates the plan)
        install(FaultPlan.from_json(
            '[{"site":"replica.spawn","action":"error","at":1,'
            '"count":1}]'))
        assert router.control_round()["decision"] == "scale_up_failed"
        assert router.num_replicas == 1
        clear()
        assert router.control_round()["decision"] == "scale_up"
        assert router.num_replicas == 2
        kinds = [e["kind"] for e in obs_events.snapshot()]
        assert "scale_up_failed" in kinds and "scale_up" in kinds
    finally:
        clear()
        router.close()


def test_scale_down_drains_then_retires_idle_replica():
    router, made = _stub_router(n=1, max_replicas=2,
                                windows_down=3,
                                scale_down_queue_depth=0.5)
    downs0 = router._c_down.collect()
    try:
        router._spawn_replica(reason="test")   # 2 live, floor is 1
        router.control_round()
        router.control_round()
        assert router.num_replicas == 2
        # third consecutive idle window retires one replica; with
        # zero in-flight load it is reaped (closed + metrics
        # reclaimed) in the same round
        assert router.control_round()["decision"] == "scale_down"
        assert router.num_replicas == 1
        assert router._c_down.collect() == downs0 + 1
        retired = [s for s in made if s.closed]
        assert len(retired) == 1 and retired[0].unregistered
        kinds = [e["kind"] for e in obs_events.snapshot()]
        assert "scale_down" in kinds and "replica_retired" in kinds
        # min_replicas floor: it never drains the last one
        for _ in range(10):
            router.control_round()
        assert router.num_replicas == 1
    finally:
        router.close()


def test_scale_down_waits_for_inflight_work():
    router, made = _stub_router(n=1, max_replicas=2, windows_down=1)
    try:
        router._spawn_replica(reason="test")
        made[0].set_load(queue=0, active=0)
        made[1].set_load(queue=0, active=2)   # busy
        assert router.control_round()["decision"] == "scale_down"
        # the idle one was picked and reaped immediately
        assert made[0].closed and not made[1].closed
        # a busy victim would have drained first: simulate by marking
        # the survivor draining with load, then finishing its work
        rep = router._replicas[0]
        rep.draining = True
        made[1].set_load(queue=0, active=1)
        router._reap_draining()
        assert not made[1].closed           # still in flight
        made[1].set_load(queue=0, active=0)
        router._reap_draining()
        assert made[1].closed
    finally:
        router.close()


# ---------------------------------------------------------------------------
# shedding: SLO policy + droppable chaos site
# ---------------------------------------------------------------------------
def test_shed_state_sheds_at_the_door_and_chaos_can_suppress_it():
    router, made = _stub_router(n=1, max_replicas=1)
    shed0 = router._c_shed.collect()
    try:
        router._shedding = True
        with pytest.raises(Overloaded):
            router.submit([1], 4)
        assert router._c_shed.collect() == shed0 + 1
        assert not made[0].submitted
        # a drop rule on router.shed suppresses the relief — the
        # request is admitted as if the policy were off (the chaos
        # model for "test the cliff")
        install(FaultPlan.from_json(
            '[{"site":"router.shed","action":"drop","at":1,'
            '"count":-1}]'))
        fut = router.submit([1], 4)
        assert fut is not None and made[0].submitted
        assert router._c_shed.collect() == shed0 + 1   # no shed tick
    finally:
        clear()
        router.close()


def test_queue_full_burst_between_rounds_counts_as_overload():
    """Verify-drive catch: a burst that fills AND drains the queues
    between two decision rounds is invisible to the sampled queue
    depth — the rejections it forced are the overload evidence."""
    router, made = _stub_router(n=1, max_replicas=2, windows_up=2)
    try:
        made[0].queue_full = True
        for _ in range(3):
            with pytest.raises(Overloaded):
                router.submit([1], 4)
        made[0].queue_full = False     # burst over: depth samples 0
        sig = router.control_round()
        assert sig["shed_delta"] == 3
        assert sig["decision"] == "hold"       # hysteresis: streak 1
        made[0].queue_full = True
        with pytest.raises(Overloaded):
            router.submit([1], 4)
        made[0].queue_full = False
        assert router.control_round()["decision"] == "scale_up"
        # POLICY sheds are the state working, not fresh evidence —
        # they must not latch shedding on while capacity is healthy
        router._shedding = True
        with pytest.raises(Overloaded):
            router.submit([1], 4)
        router.control_round()
        assert not router.shedding
    finally:
        router.close()


def test_slo_violation_counts_as_overload():
    """p99 above the knob arms scale-up even with shallow queues —
    the SLO half of the overload signal."""
    router, made = _stub_router(n=1, max_replicas=2, windows_up=1,
                                slo_p99_s=0.5,
                                scale_up_queue_depth=1e9)
    try:
        made[0].observe_latency(*([2.0] * 10))   # all above SLO
        assert router.control_round()["decision"] == "scale_up"
        assert router.num_replicas == 2
    finally:
        router.close()


def test_drain_rate_relief_discounts_depth_and_shed_evidence():
    """ISSUE 18 policy-matrix pin: with ``drain_relief_rate`` armed, a
    deep queue whose depth is FALLING faster than the rate (per
    replica, per round) is a burst already draining — its depth and
    shed-count evidence must not advance the scale-up streak or latch
    shedding.  A stalled or growing queue counts again immediately,
    and an SLO violation is never discounted (latency debt is real
    even while the queue shortens)."""
    router, made = _stub_router(n=1, max_replicas=3, windows_up=2,
                                scale_up_queue_depth=4.0,
                                drain_relief_rate=2.0)
    try:
        made[0].set_load(queue=20)     # static deep queue: overload
        sig = router.control_round()
        assert sig["queue_delta"] == 0 and sig["decision"] == "hold"
        for q in (14, 10, 7):          # draining ≥ 2 req/round
            made[0].set_load(queue=q)
            sig = router.control_round()
            assert sig["queue_delta"] < 0
            assert sig["decision"] == "hold"
        assert router.num_replicas == 1   # streak never reached 2
        # the drain stalls: depth evidence counts again, streak
        # rebuilds from zero and the second window scales up
        router.control_round()
        assert router.control_round()["decision"] == "scale_up"
        assert router.num_replicas == 2
    finally:
        router.close()


def test_drain_rate_relief_never_discounts_slo_and_defaults_off():
    router, made = _stub_router(n=1, max_replicas=1, windows_up=1,
                                slo_p99_s=0.5,
                                scale_up_queue_depth=4.0,
                                drain_relief_rate=2.0)
    try:
        # draining hard, but p99 is blown: shedding must still latch
        # (capacity is maxed, so shed is the only lever left)
        made[0].set_load(queue=20)
        router.control_round()
        made[0].set_load(queue=10)
        made[0].observe_latency(*([2.0] * 10))
        router.control_round()
        assert router.shedding
    finally:
        router.close()
    # a draining-shaped load with the knob at its 0.0 default is
    # plain overload — the relief is strictly opt-in
    router, made = _stub_router(n=1, max_replicas=1, windows_up=1,
                                scale_up_queue_depth=4.0)
    try:
        made[0].set_load(queue=20)
        router.control_round()
        made[0].set_load(queue=10)     # delta -10: no relief knob
        router.control_round()
        assert router.shedding
    finally:
        router.close()
    # the knob rides the config surface like every other policy knob
    router, _ = _stub_router(n=1, drain_relief_rate=3.5)
    cfg = router.to_config()
    router.close()
    assert cfg["drain_relief_rate"] == 3.5
    r2 = ServingRouter.from_config(cfg, lambda: _StubServer(),
                                   decision_interval_s=0)
    assert r2.drain_relief_rate == 3.5
    r2.close()


def test_predictive_scale_up_arms_on_queue_rise_before_the_level():
    """ISSUE 19 policy pin: with ``predictive_scale_rate`` armed, a
    queue RISING faster than the rate (per replica, per round) is
    overload evidence while the sampled depth is still far below
    ``scale_up_queue_depth`` — capacity spins up on the ramp, not the
    cliff.  Hysteresis still applies: one steep sample never scales."""
    router, made = _stub_router(n=1, max_replicas=3, windows_up=2,
                                scale_up_queue_depth=1e9,
                                predictive_scale_rate=2.0)
    try:
        made[0].set_load(queue=3)      # first sample: no baseline
        sig = router.control_round()
        assert sig["queue_delta"] == 0 and sig["decision"] == "hold"
        made[0].set_load(queue=6)      # +3/round >= 2.0: streak 1
        sig = router.control_round()
        assert sig["queue_delta"] == 3 and sig["decision"] == "hold"
        made[0].set_load(queue=9)      # streak 2: spawn
        assert router.control_round()["decision"] == "scale_up"
        assert router.num_replicas == 2
        # a rising queue also blocks the idle half of the policy: the
        # shallow absolute depth must not retire the fresh replica
        made[0].set_load(queue=14)
        for _ in range(12):
            assert router.control_round()["decision"] != "scale_down"
            made[0].set_load(queue=made[0].engine.scheduler
                             .queue_depth + 5)
    finally:
        router.close()


def test_predictive_scale_up_defaults_off_and_rides_config():
    # the same ramp with the knob at its 0.0 default is invisible:
    # the level-only policy is bit-identical to before
    router, made = _stub_router(n=1, max_replicas=3, windows_up=2,
                                scale_up_queue_depth=1e9)
    try:
        for q in (3, 6, 9, 12):
            made[0].set_load(queue=q)
            assert router.control_round()["decision"] == "hold"
        assert router.num_replicas == 1
    finally:
        router.close()
    # the knob rides the config surface like every other policy knob
    router, _ = _stub_router(n=1, predictive_scale_rate=1.5)
    cfg = router.to_config()
    router.close()
    assert cfg["predictive_scale_rate"] == 1.5
    r2 = ServingRouter.from_config(cfg, lambda: _StubServer(),
                                   decision_interval_s=0)
    assert r2.predictive_scale_rate == 1.5
    r2.close()


# ---------------------------------------------------------------------------
# windowed p99: cumulative-histogram diff math
# ---------------------------------------------------------------------------
def test_delta_quantile_window_math():
    prev = {"buckets": [[0.1, 5], [1.0, 5], [math.inf, 5]]}
    cur = {"buckets": [[0.1, 5], [1.0, 15], [math.inf, 15]]}
    # the 10 new observations all landed in (0.1, 1.0]
    assert _window_cum(prev, cur) == [0, 10, 10]
    p99 = _delta_quantile(prev, cur, 0.99)
    assert 0.1 < p99 <= 1.0
    # p50 interpolates midway through the landing bucket
    assert abs(_delta_quantile(prev, cur, 0.5) - 0.55) < 1e-9
    # empty window: None, never 0.0 (absence of traffic has no p99)
    assert _delta_quantile(cur, cur, 0.99) is None
    # no prev snapshot = everything is in the window
    assert _delta_quantile(None, cur, 0.99) is not None
    # +Inf landings clamp to the top finite edge
    hi = {"buckets": [[0.1, 0], [1.0, 0], [math.inf, 7]]}
    assert _delta_quantile(None, hi, 0.99) == 1.0


def test_windowed_p99_resets_each_round():
    router, made = _stub_router(n=1)
    try:
        made[0].observe_latency(0.2, 0.2, 0.2)
        router.control_round()
        first = router.windowed_p99_s()
        assert first is not None and 0.1 < first <= 1.0
        # next round saw no completions: p99 goes absent, and so does
        # the exported gauge (None scrapes absent, not stale)
        router.control_round()
        assert router.windowed_p99_s() is None
        assert router._g_p99.collect(materialize=False) is None
    finally:
        router.close()


# ---------------------------------------------------------------------------
# real servers: routing end-to-end (token-exact through the router)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_net():
    paddle.seed(0)
    cfg = gpt_tiny(use_flash_attention=False)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net, cfg


def test_router_over_real_llmservers_token_exact(tiny_net):
    net, cfg = tiny_net
    made = []

    def factory():
        s = LLMServer(net, max_batch=2, block_size=8, num_blocks=64,
                      auto_start=True)
        made.append(s)
        return s

    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).tolist()
               for n in (5, 9)]
    with ServingRouter(factory, min_replicas=1, max_replicas=1,
                       decision_interval_s=0) as router:
        futs = [router.submit(p, 6) for p in prompts]
        got = [f.result(timeout=120).tokens for f in futs]
    params = extract_decode_params(net)
    scfg = ServingModelConfig.from_gpt_config(cfg)
    for p, toks in zip(prompts, got):
        ref, _ = reference_decode(params, scfg, p, 6)
        assert toks == [int(t) for t in ref]
    assert not made[0].running       # close() stopped the replica


# ---------------------------------------------------------------------------
# ISSUE 13 acceptance: 10× Poisson burst → spawn + shed + p99 recovery
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_router_burst_scales_sheds_and_p99_recovers(tiny_net):
    """The serving half of the action-loop acceptance: a 10× Poisson
    burst against a 1-replica router must (a) spawn the second
    replica, (b) shed the excess at the door (Overloaded), and (c)
    after the burst passes, the windowed p99 must come back below
    the SLO knob — with every decision on /events over HTTP and on
    the registry."""
    net, cfg = tiny_net

    def factory():
        return LLMServer(net, max_batch=2, block_size=8,
                         num_blocks=64, max_queue=6, auto_start=True)

    reg = obs_metrics.registry()
    shed0 = reg.counter("router_shed_total").collect()
    ups0 = reg.counter("router_scale_ups_total").collect()
    router = ServingRouter(
        factory, min_replicas=1, max_replicas=2, slo_p99_s=2.0,
        scale_up_queue_depth=1.0, windows_up=2, windows_down=10 ** 6,
        cooldown_s=0.5, decision_interval_s=0.1, metrics_port=0)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (8,)).tolist()
    futs = []
    sheds = 0
    try:
        # steady trickle at a sustainable pace (~20 req/s)
        for _ in range(6):
            futs.append(router.submit(prompt, 4))
            time.sleep(0.05)
        # 10× burst: ~200 req/s Poisson arrivals
        for _ in range(120):
            try:
                futs.append(router.submit(prompt, 8))
            except Overloaded:
                sheds += 1
            time.sleep(float(rng.exponential(1.0 / 200.0)))
        assert sheds > 0, "a 10x burst against queue=6 must shed"
        # (a) the control loop spawned the second replica
        deadline = time.time() + 60
        while time.time() < deadline and router.num_replicas < 2:
            time.sleep(0.1)
        assert router.num_replicas == 2
        # drain everything that was admitted
        for f in futs:
            f.result(timeout=120)
        # (c) recovery: post-burst trickle, windowed p99 below SLO
        recovered = None
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                router.submit(prompt, 4).result(timeout=60)
            except Overloaded:
                # the door may still be shedding right after the
                # burst — back off like a real client until the
                # control loop turns the state off
                time.sleep(0.2)
                continue
            time.sleep(0.15)
            p99 = router.windowed_p99_s()
            if p99 is not None and p99 < router.slo_p99_s:
                recovered = p99
                break
        assert recovered is not None, \
            "p99 never recovered below the SLO knob"
        # every decision visible: /events over HTTP + the registry
        payload = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{router.metrics_port}/events",
            timeout=5))
        kinds = {e["kind"] for e in payload["events"]}
        assert "scale_up" in kinds
        assert reg.counter("router_shed_total").collect() >= \
            shed0 + sheds
        assert reg.counter("router_scale_ups_total").collect() == \
            ups0 + 1
        assert reg.gauge("serving_replicas").collect() == 2.0
    finally:
        router.close()
    assert router.num_replicas == 0