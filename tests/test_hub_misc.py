"""paddle.hub (local source), paddle.callbacks alias, paddle.sysconfig
(upstream python/paddle/hapi/hub.py, callbacks.py, sysconfig.py)."""

import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture()
def hub_repo(tmp_path):
    (tmp_path / "hubconf.py").write_text(textwrap.dedent("""
        dependencies = ["numpy"]

        from paddle_tpu import nn

        def tiny_mlp(hidden=4, classes=2):
            \"\"\"A tiny MLP entry point.\"\"\"
            return nn.Sequential(nn.Linear(3, hidden), nn.ReLU(),
                                 nn.Linear(hidden, classes))

        def _private_helper():
            pass
    """))
    return str(tmp_path)


def test_hub_list_help_load_local(hub_repo):
    assert paddle.hub.list(hub_repo, source="local") == ["tiny_mlp"]
    assert "tiny MLP" in paddle.hub.help(hub_repo, "tiny_mlp",
                                         source="local")
    net = paddle.hub.load(hub_repo, "tiny_mlp", source="local", hidden=8)
    from paddle_tpu.tensor import Tensor
    out = net(Tensor(np.zeros((2, 3), np.float32)))
    assert tuple(out.shape) == (2, 2)


def test_hub_refuses_network_sources(hub_repo):
    with pytest.raises(RuntimeError, match="network"):
        paddle.hub.load(hub_repo, "tiny_mlp")       # default github
    with pytest.raises(ValueError):
        paddle.hub.list(hub_repo, source="bitbucket")


def test_hub_unknown_entry_and_missing_hubconf(hub_repo, tmp_path):
    with pytest.raises(RuntimeError, match="tiny_mlp"):
        paddle.hub.load(hub_repo, "nope", source="local")
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError):
        paddle.hub.list(str(empty), source="local")


def test_hub_missing_dependency(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['no_such_pkg_xyz']\n"
        "def m():\n    return 1\n")
    with pytest.raises(RuntimeError, match="no_such_pkg_xyz"):
        paddle.hub.list(str(tmp_path), source="local")


def test_hubconf_executes_once_across_calls(tmp_path):
    marker = tmp_path / "count.txt"
    (tmp_path / "hubconf.py").write_text(textwrap.dedent(f"""
        with open({str(marker)!r}, "a") as f:
            f.write("x")

        def entry():
            return 42
    """))
    paddle.hub.list(str(tmp_path), source="local")
    assert paddle.hub.load(str(tmp_path), "entry", source="local") == 42
    with pytest.raises(RuntimeError):
        paddle.hub.load(str(tmp_path), "missing", source="local")
    assert marker.read_text() == "x", "hubconf side effects re-ran"


def test_callbacks_alias():
    from paddle_tpu.hapi import callbacks as hapi_cb
    assert paddle.callbacks.ModelCheckpoint is hapi_cb.ModelCheckpoint
    assert paddle.callbacks.EarlyStopping is hapi_cb.EarlyStopping


def test_sysconfig_paths_exist():
    assert os.path.isdir(paddle.sysconfig.get_include())
    assert os.path.isdir(paddle.sysconfig.get_lib())
