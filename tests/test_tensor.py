"""Tensor basics (modeled on upstream test/legacy_test tensor tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_dtypes():
    t = paddle.to_tensor([1.0, 2.0])
    assert t.dtype == paddle.float32
    t = paddle.to_tensor([1, 2])
    assert t.dtype == paddle.int64
    t = paddle.to_tensor(np.zeros((2, 3), dtype=np.float64))
    assert t.dtype == paddle.float64
    t = paddle.to_tensor([True, False])
    assert t.dtype == paddle.bool


def test_shape_meta():
    t = paddle.zeros([2, 3, 4])
    assert t.shape == [2, 3, 4]
    assert t.ndim == 3
    assert t.size == 24
    assert len(t) == 2


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((2.0 + a).numpy(), [3, 4])
    np.testing.assert_allclose((2.0 - a).numpy(), [1, 0])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    np.testing.assert_allclose(abs(paddle.to_tensor([-1.0])).numpy(), [1])


def test_comparison():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    assert (a > 1.5).numpy().tolist() == [False, True, True]
    assert (a == 2.0).numpy().tolist() == [False, True, False]


def test_indexing():
    t = paddle.to_tensor(np.arange(12).reshape(3, 4).astype(np.float32))
    np.testing.assert_allclose(t[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(t[1, 2].numpy(), 6)
    np.testing.assert_allclose(t[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(t[0:2, 0:2].numpy(), [[0, 1], [4, 5]])
    idx = paddle.to_tensor([0, 2])
    np.testing.assert_allclose(t[idx].numpy(), [[0, 1, 2, 3],
                                                [8, 9, 10, 11]])


def test_setitem():
    t = paddle.zeros([3, 3])
    t[1, 1] = 5.0
    assert t.numpy()[1, 1] == 5.0
    t[0] = paddle.ones([3])
    np.testing.assert_allclose(t.numpy()[0], [1, 1, 1])


def test_inplace_ops():
    t = paddle.to_tensor([1.0, 2.0])
    t.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(t.numpy(), [2, 3])
    t.scale_(2.0)
    np.testing.assert_allclose(t.numpy(), [4, 6])
    t.zero_()
    np.testing.assert_allclose(t.numpy(), [0, 0])


def test_astype_cast():
    t = paddle.to_tensor([1.7, 2.3])
    assert t.astype("int32").dtype == paddle.int32
    assert t.astype(paddle.float64).dtype == paddle.float64
    assert paddle.cast(t, "int64").dtype == paddle.int64


def test_item_and_conversion():
    t = paddle.to_tensor(3.5)
    assert t.item() == 3.5
    assert float(t) == 3.5
    assert paddle.to_tensor(2).item() == 2


def test_detach_clone():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    d = t.detach()
    assert d.stop_gradient
    c = t.clone()
    assert not c.stop_gradient  # clone tracks grad


def test_numpy_roundtrip():
    arr = np.random.rand(4, 5).astype(np.float32)
    t = paddle.to_tensor(arr)
    np.testing.assert_array_equal(t.numpy(), arr)


def test_method_parity_batch_round5():
    """Ops attached as Tensor methods (upstream patches ~300 methods;
    spot-check the round-5 batch behaves like the functional forms)."""
    import numpy as np
    from paddle_tpu.ops import _METHOD_OPS
    from paddle_tpu.ops import __dict__ as _opsns
    from paddle_tpu.tensor import Tensor

    # the attach loop skips silently — enforce the list's invariant:
    # every listed name resolves and became a callable method
    for name in _METHOD_OPS:
        assert name in _opsns, f"_METHOD_OPS names a missing op: {name}"
        assert callable(getattr(Tensor, name, None)), name

    t = Tensor(np.array([1.0, -2.0, 3.0], np.float32))
    np.testing.assert_allclose(np.asarray(t.expm1().numpy()),
                               np.expm1([1.0, -2.0, 3.0]), rtol=1e-6)
    assert tuple(t.outer(t).shape) == (3, 3)
    assert float(t.amax().numpy()) == 3.0
    cond = Tensor(np.array([True, False, True]))
    np.testing.assert_allclose(
        np.asarray(cond.where(t, t * 0).numpy()), [1.0, 0.0, 3.0])
    m = Tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
    np.testing.assert_allclose(
        np.asarray(m.kron(m).numpy()),
        np.kron(np.arange(4).reshape(2, 2), np.arange(4).reshape(2, 2)))
    for name in ("corrcoef", "cov", "quantile", "searchsorted",
                 "index_add", "renorm", "logcumsumexp"):
        assert callable(getattr(t, name)), name
