"""Detection ops: nms (vs numpy reference), roi_align (vs torchvision
semantics oracle), yolo_box, box_coder, deform_conv2d (vs plain conv
when offsets are zero)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def ref_nms(boxes, scores, thr):
    idx = np.argsort(-scores)
    keep = []
    while idx.size:
        i = idx[0]
        keep.append(i)
        if idx.size == 1:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[idx[1:], 0])
        yy1 = np.maximum(boxes[i, 1], boxes[idx[1:], 1])
        xx2 = np.minimum(boxes[i, 2], boxes[idx[1:], 2])
        yy2 = np.minimum(boxes[i, 3], boxes[idx[1:], 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        a2 = (boxes[idx[1:], 2] - boxes[idx[1:], 0]) * \
            (boxes[idx[1:], 3] - boxes[idx[1:], 1])
        iou = inter / (a1 + a2 - inter + 1e-9)
        idx = idx[1:][iou <= thr]
    return np.asarray(keep)


def _rand_boxes(rng, n, size=100):
    xy = rng.rand(n, 2) * size
    wh = rng.rand(n, 2) * (size / 3) + 2
    return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)


def test_nms_matches_reference():
    rng = np.random.RandomState(0)
    for trial in range(5):
        boxes = _rand_boxes(rng, 30)
        scores = rng.rand(30).astype(np.float32)
        got = np.asarray(paddle.vision.ops.nms(
            paddle.to_tensor(boxes), 0.5,
            paddle.to_tensor(scores)).numpy())
        want = ref_nms(boxes, scores, 0.5)
        np.testing.assert_array_equal(got, want)


def test_nms_padded_jit_safe():
    import jax
    rng = np.random.RandomState(1)
    boxes = _rand_boxes(rng, 20)
    scores = rng.rand(20).astype(np.float32)

    idx, count = V.nms_padded(paddle.to_tensor(boxes),
                              paddle.to_tensor(scores), 0.5, 10)
    want = ref_nms(boxes, scores, 0.5)[:10]
    got = np.asarray(idx.numpy())[:int(count.numpy())]
    np.testing.assert_array_equal(got, want)


def test_box_iou():
    a = np.array([[0, 0, 10, 10]], np.float32)
    b = np.array([[0, 0, 10, 10], [5, 5, 15, 15],
                  [20, 20, 30, 30]], np.float32)
    iou = V.box_iou(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(iou[0], [1.0, 25 / 175, 0.0], atol=1e-5)


def test_roi_align_uniform_field():
    # constant feature map → every roi bin must equal the constant
    x = np.full((1, 3, 16, 16), 7.0, np.float32)
    boxes = np.array([[2, 2, 10, 10], [0, 0, 15, 15]], np.float32)
    out = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                      paddle.to_tensor(np.array([2])), 4).numpy()
    assert out.shape == (2, 3, 4, 4)
    np.testing.assert_allclose(out, 7.0, atol=1e-5)


def test_roi_align_gradient_flows():
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(1, 2, 8, 8).astype(np.float32),
        stop_gradient=False)
    boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
    out = V.roi_align(x, boxes, paddle.to_tensor(np.array([1])), 2)
    out.sum().backward()
    g = x.grad.numpy()
    assert np.abs(g).sum() > 0


def test_roi_pool_shape_and_max():
    x = np.zeros((1, 1, 8, 8), np.float32)
    x[0, 0, 3, 3] = 5.0
    out = V.roi_pool(paddle.to_tensor(x),
                     paddle.to_tensor(
                         np.array([[0, 0, 7, 7]], np.float32)),
                     paddle.to_tensor(np.array([1])), 2).numpy()
    assert out.shape == (1, 1, 2, 2)
    assert out.max() == 5.0


def test_yolo_box_shapes_and_range():
    rng = np.random.RandomState(0)
    na, nc, H, W = 3, 4, 5, 5
    x = rng.randn(2, na * (5 + nc), H, W).astype(np.float32)
    img = np.array([[160, 160], [320, 320]], np.int32)
    boxes, scores = V.yolo_box(paddle.to_tensor(x),
                               paddle.to_tensor(img),
                               [10, 13, 16, 30, 33, 23], nc,
                               downsample_ratio=32)
    assert boxes.shape == [2, na * H * W, 4]
    assert scores.shape == [2, na * H * W, nc]
    b = boxes.numpy()
    assert (b[0, :, [0, 2]] <= 160).all() and (b[0] >= 0).all()
    s = scores.numpy()
    assert (s >= 0).all() and (s <= 1).all()


def test_box_coder_decode_inverts_encode():
    rng = np.random.RandomState(0)
    priors = _rand_boxes(rng, 6)
    targets = _rand_boxes(rng, 6)
    enc = V.box_coder(paddle.to_tensor(priors), None,
                      paddle.to_tensor(targets),
                      code_type="encode_center_size").numpy()
    # decode the diagonal (each target vs its own prior)
    deltas = np.stack([enc[i, i] for i in range(6)])
    dec = V.box_coder(paddle.to_tensor(priors), None,
                      paddle.to_tensor(deltas.astype(np.float32)),
                      code_type="decode_center_size").numpy()
    np.testing.assert_allclose(dec, targets, rtol=1e-4, atol=1e-3)


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],      # small → low level
                     [0, 0, 300, 300]],   # large → high level
                    np.float32)
    multi, restore, nums = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224)
    assert len(multi) == 4
    assert sum(int(n) for n in nums.numpy()) == 2
    assert multi[0].shape[0] == 1          # level 2 got the small roi
    assert multi[-1].shape[0] + multi[-2].shape[0] >= 1


def test_deform_conv2d_zero_offset_equals_conv():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    offset = np.zeros((1, 2 * 9, 6, 6), np.float32)
    got = V.deform_conv2d(paddle.to_tensor(x),
                          paddle.to_tensor(offset),
                          paddle.to_tensor(w)).numpy()
    want = paddle.conv2d(paddle.to_tensor(x), paddle.to_tensor(w)) \
        .numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_multiclass_nms():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11],
                      [50, 50, 60, 60]], np.float32)
    scores = np.array([[0.9, 0.85, 0.1],     # class 0
                       [0.2, 0.1, 0.8]],     # class 1
                      np.float32)
    out = V.multiclass_nms(paddle.to_tensor(boxes),
                           paddle.to_tensor(scores),
                           score_threshold=0.3,
                           nms_threshold=0.5).numpy()
    # class 0 keeps 1 of the two overlapping, class 1 keeps the far box
    assert out.shape[1] == 6
    labels = out[:, 0].astype(int).tolist()
    assert labels.count(0) == 1 and labels.count(1) == 1


def ref_roi_align(x, boxes, img_idx, output_size, spatial_scale=1.0,
                  sampling_ratio=2, aligned=True):
    """Exact numpy roi_align oracle (fixed sampling lattice, bilinear
    with coordinate clamping — the documented TPU semantics)."""
    ph, pw = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    out = np.zeros((R, C, ph, pw), np.float32)
    off = 0.5 if aligned else 0.0
    sr = sampling_ratio
    for r in range(R):
        img = x[img_idx[r]]
        x1, y1, x2, y2 = boxes[r] * spatial_scale - off
        rw = max(x2 - x1, 1e-3 if aligned else 1.0)
        rh = max(y2 - y1, 1e-3 if aligned else 1.0)
        bh, bw = rh / ph, rw / pw
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(C, np.float32)
                for ky in range(sr):
                    for kx in range(sr):
                        yy = min(max(y1 + i * bh + (ky + .5) / sr * bh,
                                     0), H - 1)
                        xx = min(max(x1 + j * bw + (kx + .5) / sr * bw,
                                     0), W - 1)
                        y0, x0 = int(np.floor(yy)), int(np.floor(xx))
                        y1_, x1_ = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
                        wy, wx = yy - y0, xx - x0
                        acc += (img[:, y0, x0] * (1 - wy) * (1 - wx) +
                                img[:, y0, x1_] * (1 - wy) * wx +
                                img[:, y1_, x0] * wy * (1 - wx) +
                                img[:, y1_, x1_] * wy * wx)
                out[r, :, i, j] = acc / (sr * sr)
    return out


def test_roi_align_matches_numpy_oracle():
    """ADVICE r1: verify on non-constant input against an exact oracle
    (previous tests only used constant feature maps)."""
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 16, 16).astype(np.float32)
    boxes = np.array([[1.0, 1.0, 9.0, 13.0],
                      [0.5, 2.0, 14.0, 8.0],
                      [3.0, 3.0, 12.0, 12.0]], np.float32)
    boxes_num = np.array([2, 1])
    img_idx = np.array([0, 0, 1])
    for sr in (1, 2, 4):
        got = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                          paddle.to_tensor(boxes_num), (4, 4),
                          spatial_scale=1.0, sampling_ratio=sr,
                          aligned=True).numpy()
        want = ref_roi_align(x, boxes, img_idx, (4, 4),
                             sampling_ratio=sr, aligned=True)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)
