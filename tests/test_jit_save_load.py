"""jit.save/load executable round trip (upstream .pdmodel/.pdiparams
deploy contract — SURVEY.md §3.5; the loaded program must RUN without
the original Python class)."""

import os
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, jit
from paddle_tpu.static import InputSpec
from paddle_tpu.tensor import Tensor


def test_jit_save_load_executes():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    rng = np.random.RandomState(0)
    x = rng.rand(3, 4).astype(np.float32)
    ref = np.asarray(net(Tensor(x)).numpy())

    d = tempfile.mkdtemp()
    path = os.path.join(d, "m")
    from paddle_tpu.jit.save_load import save, load
    save(net, path, input_spec=[InputSpec([3, 4], "float32")])
    assert os.path.exists(path + ".pdmodel")
    assert os.path.exists(path + ".pdiparams")

    loaded = load(path)
    out = loaded(Tensor(x))
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=1e-5)
    # weights accessible too
    sd = loaded.state_dict()
    assert any(k.endswith("weight") for k in sd)


def test_jit_load_without_program_refuses_forward():
    import pytest
    paddle.seed(0)
    net = nn.Linear(4, 2)
    d = tempfile.mkdtemp()
    path = os.path.join(d, "m")
    from paddle_tpu.jit.save_load import save, load
    save(net, path)   # no input_spec → weights only
    loaded = load(path)
    with pytest.raises(RuntimeError, match="input_spec"):
        loaded(Tensor(np.zeros((1, 4), np.float32)))


def test_jit_save_load_dynamic_batch():
    """Review finding: InputSpec([None, 4]) must export a program that
    accepts ANY batch size (symbolic dims), not just 1."""
    paddle.seed(0)
    net = nn.Linear(4, 2)
    net.eval()
    d = tempfile.mkdtemp()
    path = os.path.join(d, "m")
    from paddle_tpu.jit.save_load import save, load
    save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    loaded = load(path)
    rng = np.random.RandomState(0)
    for b in (1, 3, 7):
        x = rng.rand(b, 4).astype(np.float32)
        ref = np.asarray(net(Tensor(x)).numpy())
        out = loaded(Tensor(x))
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=1e-5)


def test_jit_save_preserves_training_mode():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    net.train()
    d = tempfile.mkdtemp()
    from paddle_tpu.jit.save_load import save
    save(net, os.path.join(d, "m"),
         input_spec=[InputSpec([2, 4], "float32")])
    assert net.training and net[1].training, \
        "jit.save left the model in eval mode"


def test_jit_save_load_multi_input_dynamic_dims():
    """Two inputs with independent dynamic dims must share one
    jax.export SymbolicScope (review finding: per-arg scopes made
    export fail and silently degrade to weights-only)."""
    import warnings

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc_a = nn.Linear(4, 2)
            self.fc_b = nn.Linear(8, 2)

        def forward(self, a, b):
            return self.fc_a(a).sum(0) + self.fc_b(b).sum(0)

    paddle.seed(0)
    net = TwoIn()
    net.eval()
    d = tempfile.mkdtemp()
    path = os.path.join(d, "m")
    from paddle_tpu.jit.save_load import save, load
    with warnings.catch_warnings():
        # only the export-degradation warning is a failure (a blanket
        # "error" filter would trip on unrelated jax warnings)
        warnings.filterwarnings("error", message="jit.save:.*")
        save(net, path, input_spec=[InputSpec([None, 4], "float32"),
                                    InputSpec([None, 8], "float32")])
    loaded = load(path)
    rng = np.random.RandomState(0)
    for ba, bb in ((1, 2), (5, 3)):
        a = rng.rand(ba, 4).astype(np.float32)
        b = rng.rand(bb, 8).astype(np.float32)
        ref = np.asarray(net(Tensor(a), Tensor(b)).numpy())
        out = loaded(Tensor(a), Tensor(b))
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=1e-5, atol=1e-6)


def test_save_load_dy2static_control_flow(tmp_path):
    """jit.save exports a dy2static-converted function (lax.cond in
    the StableHLO); load runs both branches correctly."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.static import InputSpec
    from paddle_tpu.tensor import Tensor

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 0:
                return h * 2
            return -h

    paddle.seed(0)
    net = paddle.jit.to_static(
        Net(), input_spec=[InputSpec([None, 4], "float32")])
    x = Tensor(np.ones((2, 4), np.float32))
    want = net(x).numpy()
    path = str(tmp_path / "ctrl")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 4],
                                                     "float32")])
    loaded = paddle.jit.load(path)
    got = loaded(x)
    got = got[0] if isinstance(got, (list, tuple)) else got
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=1e-5)
    # the negative branch too
    xn = Tensor(np.full((2, 4), -5.0, np.float32))
    want_n = net(xn).numpy()
    got_n = loaded(xn)
    got_n = got_n[0] if isinstance(got_n, (list, tuple)) else got_n
    np.testing.assert_allclose(np.asarray(got_n.numpy()), want_n,
                               rtol=1e-5)
