"""Quantized DCN all-reduce (EQuARX-style) on the virtual CPU mesh
(SURVEY.md §5.8 / M6; VERDICT r3 missing #8 CPU-mesh simulation)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from paddle_tpu.distributed.shard_map_compat import shard_map

from paddle_tpu.distributed.compressed import (
    quantized_all_reduce, bf16_all_reduce, compressed_psum_tree)

pytestmark = pytest.mark.dist


def _need(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("x",))


def _run_allreduce(fn, per_rank, n):
    """per_rank: [n, ...] — row r is rank r's local shard."""
    mesh = _mesh(n)
    f = shard_map(fn, mesh=mesh, in_specs=P("x"), out_specs=P("x"))
    return np.asarray(f(per_rank))


def test_int8_allreduce_matches_exact_sum():
    n = 4
    _need(n)
    rng = np.random.RandomState(0)
    per_rank = rng.randn(n, 8192).astype(np.float32)
    want = per_rank.sum(0)

    got = _run_allreduce(
        lambda x: quantized_all_reduce(x[0], "x")[None], per_rank, n)
    # noise floor: ONE direct block quantization of the exact sum
    from paddle_tpu.distributed.compressed import (_block_quant,
                                                   _block_dequant)
    q, s = _block_quant(jnp.asarray(want), 256, 8,
                        jax.random.PRNGKey(0))
    floor = np.abs(np.asarray(_block_dequant(q, s)) - want).mean()
    # the W-hop ring re-quantizes partials; error must stay within a
    # small multiple of the single-quantization floor (measured ~1.5x)
    for r in range(n):
        err = np.abs(got[r] - want).mean()
        assert err < 3 * floor, (r, err, floor)
    assert np.abs(got[0] - got[1]).max() < \
        0.1 * np.abs(want).max() + 1e-3


def test_int8_allreduce_error_is_small_and_zero_mean():
    """Stochastic rounding: bias across many trials ~0, per-element
    noise bounded by a few quantization steps."""
    n = 8
    _need(n)
    rng = np.random.RandomState(1)
    per_rank = rng.randn(n, 4096).astype(np.float32)
    want = per_rank.sum(0)
    mesh = _mesh(n)

    errs = []
    for trial in range(5):
        f = shard_map(
            lambda x, t=trial: quantized_all_reduce(
                x[0], "x", key=jax.random.PRNGKey(100 + t))[None],
            mesh=mesh, in_specs=P("x"), out_specs=P("x"))
        got = np.asarray(f(per_rank)).reshape(n, -1)[0]
        errs.append(got - want)
    errs = np.stack(errs)
    # block=256 over ~±4σ sums: scale ≈ max/127; noise ≤ ~few steps
    step = np.abs(per_rank).max() * n / 127
    assert np.abs(errs).max() < 4 * step
    assert abs(errs.mean()) < 0.05 * step


def test_int8_allreduce_is_replica_consistent():
    """Every rank must reconstruct the IDENTICAL array: the all-gather
    scatters the owner's DECODED payload, never its exact sum — an
    owner-exact copy would leave each replica's "replicated" result a
    slightly different array, silently random-walking dp-replicated
    params apart step over step (review catch on the explicit dp
    gradient path, masked there by check_vma=False)."""
    n = 4
    _need(n)
    rng = np.random.RandomState(5)
    per_rank = rng.randn(n, 2048).astype(np.float32)
    got = _run_allreduce(
        lambda x: quantized_all_reduce(x[0], "x", bits=8)[None],
        per_rank, n).reshape(n, -1)
    for r in range(1, n):
        np.testing.assert_array_equal(got[0], got[r], err_msg=str(r))


def test_bf16_allreduce_close():
    n = 4
    _need(n)
    rng = np.random.RandomState(2)
    per_rank = rng.randn(n, 1024).astype(np.float32)
    want = per_rank.sum(0)
    mesh = _mesh(n)
    f = shard_map(lambda x: bf16_all_reduce(x[0], "x")[None],
                  mesh=mesh, in_specs=P("x"), out_specs=P("x"))
    got = np.asarray(f(per_rank)).reshape(n, -1)[0]
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_compressed_tree_modes():
    n = 4
    _need(n)
    rng = np.random.RandomState(3)
    g1 = rng.randn(n, 512).astype(np.float32)
    g2 = rng.randn(n, 16, 33).astype(np.float32)   # non-multiple size
    mesh = _mesh(n)
    for mode in ("none", "bf16", "int8"):
        f = shard_map(
            lambda a, b, m=mode: jax.tree_util.tree_map(
                lambda v: v[None],
                compressed_psum_tree({"a": a[0], "b": b[0]}, "x",
                                     mode=m)),
            mesh=mesh, in_specs=(P("x"), P("x")),
            out_specs={"a": P("x"), "b": P("x")})
        out = f(g1, g2)
        tol = 0.0 if mode == "none" else 0.05
        np.testing.assert_allclose(
            np.asarray(out["a"]).reshape(n, -1)[0], g1.sum(0),
            rtol=tol + 1e-6, atol=tol * np.abs(g1.sum(0)).max() + 1e-5)
        np.testing.assert_allclose(
            np.asarray(out["b"]).reshape(n, 16, 33)[0], g2.sum(0),
            rtol=tol + 1e-6, atol=tol * np.abs(g2.sum(0)).max() + 1e-5)


def test_dp_training_step_with_compressed_grads():
    """Integration: a dp=4 data-parallel SGD step whose gradient
    all-reduce runs int8-quantized converges like the exact one."""
    n = 4
    _need(n)
    mesh = _mesh(n)
    rng = np.random.RandomState(4)
    W0 = rng.randn(16, 1).astype(np.float32) * 0.1
    Wtrue = rng.randn(16, 1).astype(np.float32)
    X = rng.randn(n * 8, 16).astype(np.float32)
    Y = X @ Wtrue

    def step(w, x, y, mode):
        def loss(w_):
            return jnp.mean((x @ w_ - y) ** 2)
        g = jax.grad(loss)(w)
        g = compressed_psum_tree({"w": g}, "x", mode=mode)["w"] / n
        return w - 0.1 * g

    for mode in ("none", "int8"):
        # w as an ARG (replicated in_spec) so the loop reuses ONE
        # compiled program; out_specs P("x") then take rank 0 — the
        # result IS replicated mathematically, but jax can't statically
        # prove it through ppermute
        f2 = jax.jit(shard_map(
            lambda w_, x, y, m=mode: step(w_, x, y, m)[None],
            mesh=mesh, in_specs=(P(), P("x"), P("x")),
            out_specs=P("x")))
        w = jnp.asarray(W0)
        for i in range(60):
            w = f2(w, X, Y)[0]
        w = np.asarray(w)
        final = float(np.mean((X @ w - Y) ** 2))
        assert final < 0.05, f"mode {mode} did not converge: {final}"
