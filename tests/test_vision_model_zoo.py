"""Model-zoo construction + forward smoke tests (upstream
test_vision_models.py analog): every family builds and produces
[N, num_classes] logits; grouped/depthwise/SE/shuffle paths execute."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M
from paddle_tpu.tensor import Tensor

pytestmark = pytest.mark.slow


def _fwd(net, size=64, train=False):
    net.train() if train else net.eval()
    x = Tensor(np.random.RandomState(0).rand(1, 3, size, size).astype(
        np.float32))
    return net(x)


@pytest.mark.parametrize("ctor,kw,size", [
    (M.resnext50_32x4d, {"num_classes": 10}, 64),
    (M.wide_resnet50_2, {"num_classes": 10}, 64),
    (M.mobilenet_v1, {"num_classes": 10}, 64),
    (M.mobilenet_v3_small, {"num_classes": 10}, 64),
    (M.mobilenet_v3_large, {"num_classes": 10}, 64),
    (M.shufflenet_v2_x0_25, {"num_classes": 10}, 64),
    (M.shufflenet_v2_swish, {"num_classes": 10}, 64),
    (M.squeezenet1_0, {"num_classes": 10}, 96),
    (M.squeezenet1_1, {"num_classes": 10}, 96),
    (M.densenet121, {"num_classes": 10}, 64),
    (M.inception_v3, {"num_classes": 10}, 96),
])
def test_model_zoo_forward(ctor, kw, size):
    paddle.seed(0)
    net = ctor(**kw)
    out = _fwd(net, size=size)
    assert out.shape == [1, 10]
    assert np.isfinite(np.asarray(out.numpy())).all()
    assert len(list(net.parameters())) > 10


def test_googlenet_aux_heads_both_modes():
    """Upstream returns (out, aux1, aux2) in BOTH train and eval."""
    paddle.seed(0)
    net = M.googlenet(num_classes=10)
    for train in (True, False):
        out = _fwd(net, size=96, train=train)
        assert isinstance(out, tuple) and len(out) == 3
        assert all(o.shape == [1, 10] for o in out)


def test_basic_block_rejects_groups():
    import pytest as _pytest
    with _pytest.raises(ValueError, match="BasicBlock"):
        M.resnet18(groups=32, width=4)


def test_resnext152_64x4d_exists():
    assert callable(M.resnext152_64x4d)


def test_pretrained_refuses_offline():
    with pytest.raises(RuntimeError, match="pretrained"):
        M.densenet121(pretrained=True)


def test_densenet_variant_channels():
    # densenet161 switches to growth 48 / 96-channel stem
    net = M.densenet161(num_classes=4)
    out = _fwd(net, size=64)
    assert out.shape == [1, 4]


def test_vgg11_13_and_resnext101_32x8d_torchvision_param_parity():
    """New zoo entries match torchvision parameter counts exactly
    (the structural-identity oracle)."""
    import numpy as np
    import paddle_tpu as paddle
    paddle.seed(0)
    feats = M.vgg11(num_classes=0, with_pool=False)
    n = sum(int(np.prod(p.shape)) for p in feats.parameters())
    assert n == 9_220_480                  # torchvision vgg11.features
    feats = M.vgg13(num_classes=0, with_pool=False)
    n = sum(int(np.prod(p.shape)) for p in feats.parameters())
    assert n == 9_404_992
    net = M.resnext101_32x8d(num_classes=1000)
    n = sum(int(np.prod(p.shape)) for p in net.parameters())
    assert n == 88_791_336                 # torchvision resnext101_32x8d
