"""paddle.sparse: constructors, conversions, ops, sparse nn."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _dense():
    d = np.zeros((3, 4), np.float32)
    d[0, 1] = 2.0
    d[1, 3] = -1.5
    d[2, 0] = 4.0
    return d


def test_coo_roundtrip():
    d = _dense()
    idx = np.asarray(np.nonzero(d))
    vals = d[tuple(idx)]
    s = sparse.sparse_coo_tensor(idx, vals, d.shape)
    assert s.is_sparse() and s.is_sparse_coo()
    assert s.shape == [3, 4] and s.nnz() == 3
    np.testing.assert_allclose(s.to_dense().numpy(), d)
    np.testing.assert_array_equal(s.indices().numpy(), idx)
    np.testing.assert_allclose(s.values().numpy(), vals)


def test_csr_roundtrip_and_convert():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_csr()
    assert s.is_sparse_csr()
    np.testing.assert_allclose(s.to_dense().numpy(), d)
    coo = s.to_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(), d)
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(back.to_dense().numpy(), d)


def test_dense_to_sparse_and_back():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    assert s.nnz() == 3
    np.testing.assert_allclose(s.to_dense().numpy(), d)


def test_sparse_add_subtract():
    d1, d2 = _dense(), _dense() * 2
    d2[0, 0] = 9.0  # different pattern
    s1 = paddle.to_tensor(d1).to_sparse_coo()
    s2 = paddle.to_tensor(np.asarray(d2)).to_sparse_coo()
    np.testing.assert_allclose(sparse.add(s1, s2).to_dense().numpy(),
                               d1 + d2)
    np.testing.assert_allclose(
        sparse.subtract(s1, s2).to_dense().numpy(), d1 - d2)


def test_sparse_scalar_multiply_divide():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    np.testing.assert_allclose(sparse.multiply(s, 3.0)
                               .to_dense().numpy(), d * 3)
    np.testing.assert_allclose(sparse.divide(s, 2.0)
                               .to_dense().numpy(), d / 2)


def test_sparse_dense_matmul():
    d = _dense()
    w = np.random.RandomState(0).rand(4, 5).astype(np.float32)
    s = paddle.to_tensor(d).to_sparse_coo()
    out = sparse.matmul(s, paddle.to_tensor(w))
    np.testing.assert_allclose(out.numpy(), d @ w, rtol=1e-5)
    # csr path
    sc = paddle.to_tensor(d).to_sparse_csr()
    out2 = sparse.matmul(sc, paddle.to_tensor(w))
    np.testing.assert_allclose(out2.numpy(), d @ w, rtol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(0)
    x = rng.rand(3, 4).astype(np.float32)
    y = rng.rand(4, 3).astype(np.float32)
    mask_d = np.zeros((3, 3), np.float32)
    mask_d[0, 1] = 1
    mask_d[2, 2] = 1
    mask = paddle.to_tensor(mask_d).to_sparse_coo()
    out = sparse.masked_matmul(paddle.to_tensor(x),
                               paddle.to_tensor(y), mask)
    full = x @ y
    want = np.zeros_like(full)
    want[0, 1] = full[0, 1]
    want[2, 2] = full[2, 2]
    np.testing.assert_allclose(out.to_dense().numpy(), want, rtol=1e-5)


def test_sparse_relu_and_transpose():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    np.testing.assert_allclose(sparse.relu(s).to_dense().numpy(),
                               np.maximum(d, 0))
    np.testing.assert_allclose(
        sparse.transpose(s, [1, 0]).to_dense().numpy(), d.T)


def test_sparse_nn_relu_softmax():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    out = sparse.nn.ReLU()(s)
    np.testing.assert_allclose(out.to_dense().numpy(), np.maximum(d, 0))
    sm = sparse.nn.Softmax()(s)
    got = sm.to_dense().numpy()
    # softmax over nonzeros of each row
    for r in range(3):
        nz = d[r] != 0
        e = np.exp(d[r][nz] - d[r][nz].max())
        np.testing.assert_allclose(got[r][nz], e / e.sum(), rtol=1e-5)


def test_sparse_sparse_matmul_returns_sparse():
    """ADVICE r1: COO @ COO must return a sparse result (upstream
    paddle.sparse.matmul parity), not a silently densified Tensor."""
    from paddle_tpu import sparse
    from paddle_tpu.sparse import SparseCooTensor

    rng = np.random.RandomState(0)
    a = rng.rand(4, 5).astype(np.float32) * (rng.rand(4, 5) > 0.5)
    b = rng.rand(5, 3).astype(np.float32) * (rng.rand(5, 3) > 0.5)
    sa = paddle.to_tensor(a).to_sparse_coo(2)
    sb = paddle.to_tensor(b).to_sparse_coo(2)
    out = sparse.matmul(sa, sb)
    assert isinstance(out, SparseCooTensor)
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               a @ b, rtol=1e-5, atol=1e-6)
