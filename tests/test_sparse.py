"""paddle.sparse: constructors, conversions, ops, sparse nn."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _dense():
    d = np.zeros((3, 4), np.float32)
    d[0, 1] = 2.0
    d[1, 3] = -1.5
    d[2, 0] = 4.0
    return d


def test_coo_roundtrip():
    d = _dense()
    idx = np.asarray(np.nonzero(d))
    vals = d[tuple(idx)]
    s = sparse.sparse_coo_tensor(idx, vals, d.shape)
    assert s.is_sparse() and s.is_sparse_coo()
    assert s.shape == [3, 4] and s.nnz() == 3
    np.testing.assert_allclose(s.to_dense().numpy(), d)
    np.testing.assert_array_equal(s.indices().numpy(), idx)
    np.testing.assert_allclose(s.values().numpy(), vals)


def test_csr_roundtrip_and_convert():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_csr()
    assert s.is_sparse_csr()
    np.testing.assert_allclose(s.to_dense().numpy(), d)
    coo = s.to_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(), d)
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(back.to_dense().numpy(), d)


def test_dense_to_sparse_and_back():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    assert s.nnz() == 3
    np.testing.assert_allclose(s.to_dense().numpy(), d)


def test_sparse_add_subtract():
    d1, d2 = _dense(), _dense() * 2
    d2[0, 0] = 9.0  # different pattern
    s1 = paddle.to_tensor(d1).to_sparse_coo()
    s2 = paddle.to_tensor(np.asarray(d2)).to_sparse_coo()
    np.testing.assert_allclose(sparse.add(s1, s2).to_dense().numpy(),
                               d1 + d2)
    np.testing.assert_allclose(
        sparse.subtract(s1, s2).to_dense().numpy(), d1 - d2)


def test_sparse_scalar_multiply_divide():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    np.testing.assert_allclose(sparse.multiply(s, 3.0)
                               .to_dense().numpy(), d * 3)
    np.testing.assert_allclose(sparse.divide(s, 2.0)
                               .to_dense().numpy(), d / 2)


def test_sparse_dense_matmul():
    d = _dense()
    w = np.random.RandomState(0).rand(4, 5).astype(np.float32)
    s = paddle.to_tensor(d).to_sparse_coo()
    out = sparse.matmul(s, paddle.to_tensor(w))
    np.testing.assert_allclose(out.numpy(), d @ w, rtol=1e-5)
    # csr path
    sc = paddle.to_tensor(d).to_sparse_csr()
    out2 = sparse.matmul(sc, paddle.to_tensor(w))
    np.testing.assert_allclose(out2.numpy(), d @ w, rtol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(0)
    x = rng.rand(3, 4).astype(np.float32)
    y = rng.rand(4, 3).astype(np.float32)
    mask_d = np.zeros((3, 3), np.float32)
    mask_d[0, 1] = 1
    mask_d[2, 2] = 1
    mask = paddle.to_tensor(mask_d).to_sparse_coo()
    out = sparse.masked_matmul(paddle.to_tensor(x),
                               paddle.to_tensor(y), mask)
    full = x @ y
    want = np.zeros_like(full)
    want[0, 1] = full[0, 1]
    want[2, 2] = full[2, 2]
    np.testing.assert_allclose(out.to_dense().numpy(), want, rtol=1e-5)


def test_sparse_relu_and_transpose():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    np.testing.assert_allclose(sparse.relu(s).to_dense().numpy(),
                               np.maximum(d, 0))
    np.testing.assert_allclose(
        sparse.transpose(s, [1, 0]).to_dense().numpy(), d.T)


def test_sparse_nn_relu_softmax():
    d = _dense()
    s = paddle.to_tensor(d).to_sparse_coo()
    out = sparse.nn.ReLU()(s)
    np.testing.assert_allclose(out.to_dense().numpy(), np.maximum(d, 0))
    sm = sparse.nn.Softmax()(s)
    got = sm.to_dense().numpy()
    # softmax over nonzeros of each row
    for r in range(3):
        nz = d[r] != 0
        e = np.exp(d[r][nz] - d[r][nz].max())
        np.testing.assert_allclose(got[r][nz], e / e.sum(), rtol=1e-5)


def test_sparse_sparse_matmul_returns_sparse():
    """ADVICE r1: COO @ COO must return a sparse result (upstream
    paddle.sparse.matmul parity), not a silently densified Tensor."""
    from paddle_tpu import sparse
    from paddle_tpu.sparse import SparseCooTensor

    rng = np.random.RandomState(0)
    a = rng.rand(4, 5).astype(np.float32) * (rng.rand(4, 5) > 0.5)
    b = rng.rand(5, 3).astype(np.float32) * (rng.rand(5, 3) > 0.5)
    sa = paddle.to_tensor(a).to_sparse_coo(2)
    sb = paddle.to_tensor(b).to_sparse_coo(2)
    out = sparse.matmul(sa, sb)
    assert isinstance(out, SparseCooTensor)
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               a @ b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# SelectedRows sparse gradients (upstream phi::SelectedRows +
# embedding_sparse_grad + sgd/adam sparse kernels)
# ---------------------------------------------------------------------------
def test_selected_rows_embedding_grad():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.framework.selected_rows import SelectedRows
    from paddle_tpu.tensor import Tensor

    paddle.seed(0)
    emb = nn.Embedding(100, 8, sparse=True)
    ids = Tensor(np.asarray([[1, 5, 5], [7, 1, 99]], dtype=np.int64))
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    assert g.height == 100 and g.values.shape[1] == 8
    dense = np.asarray(g.to_dense())
    # rows 1 and 5 looked up twice -> grad 2.0 everywhere in the row
    np.testing.assert_allclose(dense[1], 2.0 * np.ones(8))
    np.testing.assert_allclose(dense[5], 2.0 * np.ones(8))
    np.testing.assert_allclose(dense[7], np.ones(8))
    np.testing.assert_allclose(dense[0], np.zeros(8))


def test_selected_rows_sgd_matches_dense():
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.tensor import Tensor

    ids = np.asarray([[3, 4, 3]], dtype=np.int64)

    def run(sparse):
        paddle.seed(0)
        emb = nn.Embedding(20, 4, sparse=sparse)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=emb.parameters())
        for _ in range(3):
            loss = (emb(Tensor(ids)) ** 2.0).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return np.asarray(emb.weight.numpy())

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5)


def test_selected_rows_adam_lazy_touches_only_rows():
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.tensor import Tensor

    ids = np.asarray([[2, 9]], dtype=np.int64)
    paddle.seed(0)
    emb = nn.Embedding(16, 4, sparse=True)
    w0 = np.asarray(emb.weight.numpy()).copy()
    opt = optimizer.Adam(learning_rate=0.05, lazy_mode=True,
                         parameters=emb.parameters())
    loss = (emb(Tensor(ids)) ** 2.0).sum()
    loss.backward()
    opt.step()
    w1 = np.asarray(emb.weight.numpy())
    changed = np.any(w1 != w0, axis=1)
    assert changed[2] and changed[9]
    untouched = [i for i in range(16) if i not in (2, 9)]
    np.testing.assert_allclose(w1[untouched], w0[untouched])


def test_selected_rows_with_global_norm_clip():
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.tensor import Tensor

    paddle.seed(0)
    emb = nn.Embedding(10, 4, sparse=True)
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=emb.parameters(),
                        grad_clip=nn.ClipGradByGlobalNorm(0.01))
    loss = (emb(Tensor(np.asarray([[1, 2]], np.int64))) ** 2.0).sum()
    loss.backward()
    opt.step()   # must not raise; update magnitude bounded by the clip
    assert np.isfinite(np.asarray(emb.weight.numpy())).all()


def test_tensor_array_shim():
    import paddle_tpu as paddle
    from paddle_tpu import ops
    from paddle_tpu.tensor import Tensor

    arr = ops.create_array("float32")
    ops.array_write(Tensor(np.ones(3, np.float32)), 0, arr)
    ops.array_write(Tensor(2 * np.ones(3, np.float32)), 1, arr)
    assert int(ops.array_length(arr)) == 2
    back = ops.array_read(arr, 1)
    np.testing.assert_allclose(np.asarray(back.numpy()), 2 * np.ones(3))
    stacked = arr.stack()
    assert tuple(stacked.shape) == (2, 3)
    import pytest as _pytest
    with _pytest.raises(IndexError):
        ops.array_write(Tensor(np.ones(3, np.float32)), 5, arr)
