"""Optimizer + LR scheduler tests (pattern: upstream
test_sgd_op/test_adam_op + test_lr_scheduler)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _quadratic_setup(opt_cls, lr=0.1, **kw):
    w = paddle.to_tensor([5.0], stop_gradient=False)
    w.name = "w"

    class P:
        pass

    # wrap as a pseudo-parameter
    from paddle_tpu.tensor import Parameter
    p = Parameter(np.array([5.0], dtype=np.float32), name="w")
    opt = opt_cls(learning_rate=lr, parameters=[p], **kw)
    return p, opt


@pytest.mark.parametrize("opt_cls,kw", [
    (optimizer.SGD, {}),
    (optimizer.Momentum, {"momentum": 0.9}),
    (optimizer.Adam, {}),
    (optimizer.AdamW, {}),
    (optimizer.RMSProp, {}),
    (optimizer.Adagrad, {"learning_rate": 1.0}),
    (optimizer.Lamb, {"learning_rate": 0.05}),
])
def test_optimizers_minimize_quadratic(opt_cls, kw):
    kw = dict(kw)
    lr = kw.pop("learning_rate", 0.1)
    p, opt = _quadratic_setup(opt_cls, lr=lr, **kw)
    for _ in range(100):
        loss = (p * p).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert abs(p.numpy()[0]) < 1.0, f"{opt_cls.__name__}: {p.numpy()}"


def test_sgd_exact_update():
    p, opt = _quadratic_setup(optimizer.SGD, lr=0.1)
    loss = (p * p).sum()  # grad = 2w = 10
    loss.backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [4.0], rtol=1e-6)


def test_adam_matches_reference_formula():
    from paddle_tpu.tensor import Parameter
    w0 = np.array([1.0, -2.0], dtype=np.float32)
    g = np.array([0.5, 0.3], dtype=np.float32)
    p = Parameter(w0.copy())
    opt = optimizer.Adam(learning_rate=0.1, parameters=[p])
    p.grad = paddle.to_tensor(g)
    opt.step()
    # reference: paddle adam epsilon inside sqrt-scaled denom
    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.1
    m = (1 - b1) * g
    v = (1 - b2) * g * g
    lr_t = lr * np.sqrt(1 - b2) / (1 - b1)
    expect = w0 - lr_t * m / (np.sqrt(v) + eps * np.sqrt(1 - b2))
    np.testing.assert_allclose(p.numpy(), expect, rtol=1e-5)


def test_weight_decay_l2_vs_decoupled():
    from paddle_tpu.tensor import Parameter
    p1 = Parameter(np.array([1.0], dtype=np.float32))
    opt1 = optimizer.SGD(learning_rate=0.1, parameters=[p1],
                         weight_decay=0.1)
    p1.grad = paddle.to_tensor(np.array([0.0], dtype=np.float32))
    opt1.step()
    # L2: w -= lr * (g + wd*w) = 1 - 0.1*0.1 = 0.99
    np.testing.assert_allclose(p1.numpy(), [0.99], rtol=1e-6)


def test_grad_clip_global_norm():
    from paddle_tpu.tensor import Parameter
    clip = nn.ClipGradByGlobalNorm(1.0)
    p = Parameter(np.array([1.0], dtype=np.float32))
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p], grad_clip=clip)
    p.grad = paddle.to_tensor(np.array([10.0], dtype=np.float32))
    opt.step()
    # clipped grad = 10/10 = 1 → w = 0
    np.testing.assert_allclose(p.numpy(), [0.0], atol=1e-5)


def test_optimizer_state_dict_roundtrip():
    from paddle_tpu.tensor import Parameter
    p = Parameter(np.array([1.0], dtype=np.float32), name="p0")
    opt = optimizer.Adam(learning_rate=0.1, parameters=[p])
    p.grad = paddle.to_tensor(np.array([0.5], dtype=np.float32))
    opt.step()
    sd = opt.state_dict()
    p2 = Parameter(np.array([1.0], dtype=np.float32), name="p0")
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=[p2])
    opt2.set_state_dict(sd)
    assert np.allclose(opt2._state["p0"]["moment1"],
                       opt._state["p0"]["moment1"])


def test_lr_schedulers():
    lr = optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    vals = []
    for _ in range(5):
        vals.append(round(lr.get_lr(), 6))
        lr.step()
    assert vals == [0.1, 0.1, 0.05, 0.05, 0.025]

    warm = optimizer.lr.LinearWarmup(0.1, warmup_steps=4, start_lr=0.0,
                                     end_lr=0.1)
    v0 = warm.get_lr()
    warm.step()
    warm.step()
    warm.step()
    warm.step()
    assert v0 == 0.0 and abs(warm.get_lr() - 0.1) < 1e-9

    cos = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(cos.get_lr() - 1.0) < 1e-9

    noam = optimizer.lr.NoamDecay(d_model=512, warmup_steps=10,
                                  learning_rate=1.0)
    assert noam.get_lr() > 0


def test_scheduler_drives_optimizer():
    from paddle_tpu.tensor import Parameter
    sched = optimizer.lr.StepDecay(0.5, step_size=1, gamma=0.1)
    p = Parameter(np.array([1.0], dtype=np.float32))
    opt = optimizer.SGD(learning_rate=sched, parameters=[p])
    assert opt.get_lr() == 0.5
    sched.step()
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_multi_precision_master_weights():
    from paddle_tpu.tensor import Parameter
    p = Parameter(np.array([1.0], dtype=np.float32))
    p._value = p._value.astype("bfloat16")
    opt = optimizer.AdamW(learning_rate=0.01, parameters=[p],
                          multi_precision=True)
    p.grad = paddle.to_tensor(np.array([0.5], dtype=np.float32)
                              ).astype("bfloat16")
    opt.step()
    st = opt._state[p.name]
    assert "master_weight" in st
    assert str(st["master_weight"].dtype) == "float32"
    assert p.dtype == paddle.bfloat16


def test_set_state_dict_subset_not_remapped():
    """ADVICE r1: a checkpoint holding state for a SUBSET of params with
    matching names must be restored by name, never positionally."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    paddle.seed(0)
    net = nn.Linear(3, 3)
    opt = optimizer.Adam(1e-3, parameters=net.parameters())
    names = [p.name for p in net.parameters()]
    # checkpoint contains moment state for only the SECOND param
    m = np.full((3,), 7.0, np.float32)
    opt.set_state_dict({f"{names[1]}.moment1": paddle.to_tensor(m)})
    assert names[1] in opt._state
    np.testing.assert_allclose(
        np.asarray(opt._state[names[1]]["moment1"]), m)
    assert names[0] not in opt._state or \
        "moment1" not in opt._state.get(names[0], {})


def test_set_state_dict_cross_process_remap_warns():
    """Pure cross-process case: NO name matches and counts agree →
    positional remap, with a warning."""
    import warnings as _warnings
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    paddle.seed(0)
    net = nn.Linear(3, 3)
    opt = optimizer.Adam(1e-3, parameters=net.parameters())
    names = [p.name for p in net.parameters()]
    sd = {}
    for i in range(len(names)):
        sd[f"other_{i}.moment1"] = paddle.to_tensor(
            np.full((3, 3) if i == 0 else (3,), float(i + 1), np.float32))
    with _warnings.catch_warnings(record=True) as w:
        _warnings.simplefilter("always")
        opt.set_state_dict(sd)
    assert any("remapping" in str(x.message) for x in w)
    np.testing.assert_allclose(
        np.asarray(opt._state[names[0]]["moment1"]),
        np.full((3, 3), 1.0, np.float32))


def test_state_dict_fresh_after_eager_steps_post_restore():
    """Review finding: after a restore (which populates the jit-engine
    state slot) followed by EAGER training steps, state_dict must carry
    the live eager moments, not the stale restore-time tree."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.tensor import Tensor

    paddle.seed(0)
    net = nn.Linear(4, 3)
    opt = optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    rng = np.random.RandomState(0)
    x = Tensor(rng.rand(8, 4).astype(np.float32))

    def one_step(o, n):
        loss = (n(x) ** 2.0).mean()
        loss.backward()
        o.step()
        o.clear_grad()

    one_step(opt, net)
    sd = {k: (v.numpy().copy() if hasattr(v, "numpy") else v)
          for k, v in opt.state_dict().items()}

    paddle.seed(1)
    net2 = nn.Linear(4, 3)
    opt2 = optimizer.Adam(learning_rate=1e-2,
                          parameters=net2.parameters())
    opt2.set_state_dict(opt.state_dict())
    for _ in range(3):
        one_step(opt2, net2)
    sd2 = opt2.state_dict()
    # param_N numbering differs across optimizer instances: compare the
    # moment1 slots positionally (ordinal order is the stable identity)
    def moments(d):
        keys = sorted(k for k in d if k.endswith(".moment1"))
        return [np.asarray(d[k].numpy() if hasattr(d[k], "numpy")
                           else d[k]) for k in keys]

    m1, m2 = moments(sd), moments(sd2)
    assert m1 and len(m1) == len(m2)
    changed = any(not np.allclose(a, b) for a, b in zip(m1, m2))
    assert changed, ("state_dict returned stale restore-time moments "
                     "after eager steps")


def test_adam_adamw_torch_oracle_epsilon_placement():
    """Settles the epsilon-placement question (VERDICT r4 next #7):
    paddle's kernel form  lr_t = lr*sqrt(1-b2^t)/(1-b1^t),
    denom = sqrt(m2) + eps*sqrt(1-b2^t)  is algebraically the
    bias-corrected-hat form  m1hat/(sqrt(m2hat)+eps)  that torch (and
    upstream paddle/phi adam_functors) implement.  A LARGE eps (1e-2)
    amplifies any placement mismatch; 5 steps, exact trajectory."""
    import torch
    from paddle_tpu.tensor import Parameter

    w0 = np.array([0.7, -1.3, 2.1], np.float32)
    grads = [np.array([0.5, -0.2, 0.9], np.float32) * (i + 1)
             for i in range(5)]
    eps, lr = 1e-2, 0.1

    for cls, tcls, kw, tkw in [
            (optimizer.Adam, torch.optim.Adam, {}, {}),
            (optimizer.AdamW, torch.optim.AdamW,
             {"weight_decay": 0.05}, {"weight_decay": 0.05})]:
        p = Parameter(w0.copy())
        opt = cls(learning_rate=lr, parameters=[p], epsilon=eps, **kw)
        tp = torch.nn.Parameter(torch.tensor(w0.copy()))
        topt = tcls([tp], lr=lr, eps=eps, **tkw)
        for g in grads:
            p.grad = paddle.to_tensor(g)
            opt.step()
            opt.clear_grad()
            tp.grad = torch.tensor(g)
            topt.step()
            topt.zero_grad()
        np.testing.assert_allclose(
            p.numpy(), tp.detach().numpy(), rtol=2e-5, atol=2e-6,
            err_msg=f"{cls.__name__} diverges from torch oracle")
