"""Static-graph TRAINING (upstream Executor.run on a Program containing
optimizer.minimize — test/legacy/test_optimizer.py style; VERDICT r3
next #5): a classic enable_static() train loop must converge, with the
whole fwd+bwd+update step compiled as one XLA program."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _linreg_program(opt_factory):
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        fc = nn.Linear(4, 1)
        pred = fc(x)
        loss = paddle.mean((pred - y) ** 2)
        opt = opt_factory(fc.parameters())
        opt.minimize(loss)
    return main, startup, loss, fc


def _make_data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    w = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    X = rng.randn(n, 4).astype(np.float32)
    Y = X @ w + 0.1
    return X, Y


def test_static_sgd_linear_regression_converges():
    main, startup, loss, fc = _linreg_program(
        lambda ps: optimizer.SGD(learning_rate=0.1, parameters=ps))
    exe = static.Executor()
    exe.run(startup)
    X, Y = _make_data()
    first = None
    for i in range(60):
        (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        if first is None:
            first = float(lv)
    assert first > 0.5
    assert float(lv) < 0.02, f"did not converge: {float(lv)}"


def test_static_adam_train_and_param_fetch():
    main, startup, loss, fc = _linreg_program(
        lambda ps: optimizer.Adam(learning_rate=0.1, parameters=ps))
    exe = static.Executor()
    X, Y = _make_data()
    w0 = fc.weight.numpy().copy()
    for i in range(150):
        lv, w = exe.run(main, feed={"x": X, "y": Y},
                        fetch_list=[loss, fc.weight])
    # param fetch returns the post-update value, and the live Parameter
    # was committed (visible to the eager world)
    assert not np.allclose(w, w0)
    np.testing.assert_allclose(w, fc.weight.numpy(), rtol=1e-6)
    assert float(lv) < 0.05


def test_static_train_loss_is_pre_update():
    """Fetched loss is this step's loss (computed with pre-update
    params), so two identical runs show strictly decreasing loss."""
    main, startup, loss, fc = _linreg_program(
        lambda ps: optimizer.SGD(learning_rate=0.1, parameters=ps))
    exe = static.Executor()
    X, Y = _make_data()
    (l1,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    (l2,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    assert float(l2) < float(l1)


def test_minimize_unrecorded_loss_refuses():
    from paddle_tpu.tensor import Tensor
    main = static.Program()
    with static.program_guard(main):
        fc = nn.Linear(2, 1)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=fc.parameters())
        loose = Tensor(np.zeros((1,), np.float32))
        with pytest.raises(RuntimeError, match="not recorded"):
            opt.minimize(loose)


def test_static_mlp_classification_converges():
    """LeNet-class check scaled down: a 2-layer MLP on separable blobs
    under enable_static() (upstream static LeNet loop analog)."""
    rng = np.random.RandomState(1)
    X = np.concatenate([rng.randn(32, 8) + 2, rng.randn(32, 8) - 2]) \
        .astype(np.float32)
    Y = np.concatenate([np.zeros(32), np.ones(32)]).astype(np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        y = static.data("y", [None], "int64")
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        logits = net(x)
        loss = nn.functional.cross_entropy(logits, y)
        opt = optimizer.Adam(learning_rate=0.05,
                             parameters=net.parameters())
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    for i in range(30):
        (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    assert float(lv) < 0.1, f"did not converge: {float(lv)}"


def test_clone_for_test_does_not_train():
    """Upstream eval pattern: clone(for_test=True) must never update
    parameters or optimizer state."""
    main, startup, loss, fc = _linreg_program(
        lambda ps: optimizer.SGD(learning_rate=0.1, parameters=ps))
    exe = static.Executor()
    X, Y = _make_data()
    test_prog = main.clone(for_test=True)
    w0 = fc.weight.numpy().copy()
    (l1,) = exe.run(test_prog, feed={"x": X, "y": Y}, fetch_list=[loss])
    (l2,) = exe.run(test_prog, feed={"x": X, "y": Y}, fetch_list=[loss])
    np.testing.assert_array_equal(fc.weight.numpy(), w0)
    np.testing.assert_allclose(float(l1), float(l2))
    # the original program still trains
    (l3,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    assert not np.allclose(fc.weight.numpy(), w0)


def test_static_training_optimizer_state_checkpoints():
    """state_dict after static steps carries live Adam moments, and a
    restored checkpoint seeds the next static run (resume contract)."""
    main, startup, loss, fc = _linreg_program(
        lambda ps: optimizer.Adam(learning_rate=0.05, parameters=ps))
    exe = static.Executor()
    X, Y = _make_data()
    for _ in range(3):
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    opt = main._train["opt"]
    sd = opt.state_dict()
    moment_keys = [k for k in sd if k.endswith(".moment1")]
    assert moment_keys, f"no moments in state_dict: {list(sd)[:6]}"
    assert any(np.abs(np.asarray(sd[k].numpy())).sum() > 0
               for k in moment_keys), "moments are all zero"

    # resume into a fresh program/optimizer
    paddle.disable_static()
    paddle.enable_static()
    main2, startup2, loss2, fc2 = _linreg_program(
        lambda ps: optimizer.Adam(learning_rate=0.05, parameters=ps))
    fc2.set_state_dict(fc.state_dict())
    opt2 = main2._train["opt"]
    opt2.set_state_dict(sd)
    # restored moments visible before any step
    assert any(
        float(np.abs(np.asarray(v)).sum()) > 0
        for stt in opt2._state.values() for k, v in stt.items()
        if k == "moment1"), "set_state_dict did not restore moments"
    exe2 = static.Executor()
    (lv,) = exe2.run(main2, feed={"x": X, "y": Y}, fetch_list=[loss2])
    st = main2._train["state"]

    # a fresh (no-restore) single step for comparison
    paddle.disable_static()
    paddle.enable_static()
    main3, _, loss3, fc3 = _linreg_program(
        lambda ps: optimizer.Adam(learning_rate=0.05, parameters=ps))
    fc3.set_state_dict(fc.state_dict())
    static.Executor().run(main3, feed={"x": X, "y": Y},
                          fetch_list=[loss3])
    st3 = main3._train["state"]
    # same params, same data, same step count since restore — the only
    # difference is the seeded moments, which must change the state
    diffs = [float(np.abs(np.asarray(st[n]["moment1"]) -
                          np.asarray(st3[m]["moment1"])).sum())
             for n, m in zip(st, st3)]
    assert max(diffs) > 1e-6, "restored moments had no effect"


def test_static_training_honors_param_lr_and_clip():
    """ParamAttr learning_rate=0 freezes a param; global-norm clip is
    applied inside the compiled step."""
    from paddle_tpu import nn as pnn
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        fc = pnn.Linear(4, 1)
        fc.weight.optimize_attr["learning_rate"] = 0.0   # frozen lr
        pred = fc(x)
        loss = paddle.mean((pred - y) ** 2)
        opt = optimizer.SGD(
            learning_rate=0.1, parameters=fc.parameters(),
            grad_clip=pnn.ClipGradByGlobalNorm(1e-8))
        opt.minimize(loss)
    exe = static.Executor()
    X, Y = _make_data()
    w0 = fc.weight.numpy().copy()
    b0 = fc.bias.numpy().copy()
    exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
    # weight frozen by per-param lr=0; bias moved by at most the tiny
    # clipped norm
    np.testing.assert_array_equal(fc.weight.numpy(), w0)
    assert np.abs(fc.bias.numpy() - b0).max() < 1e-6
    assert np.abs(fc.bias.numpy() - b0).max() > 0


def test_static_nn_builders_train():
    """Classic static script style: static.nn.fc/batch_norm/conv2d
    builders + minimize under program_guard (upstream
    static/nn/common.py surface)."""
    rng = np.random.RandomState(0)
    X = rng.rand(32, 1, 8, 8).astype(np.float32)
    Y = (X.mean((1, 2, 3)) > 0.5).astype(np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 1, 8, 8], "float32")
        y = static.data("y", [None], "int64")
        h = static.nn.conv2d(x, num_filters=4, filter_size=3,
                             padding=1, act="relu")
        h = static.nn.batch_norm(h)
        h = nn.functional.adaptive_avg_pool2d(h, 1)
        h = static.nn.fc(h, size=2)
        loss = nn.functional.cross_entropy(h, y)
        opt = optimizer.Adam(learning_rate=0.05,
                             parameters=_collect_params(main))
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    first = None
    for _ in range(25):
        (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        if first is None:
            first = float(lv)
    assert float(lv) < first, (first, float(lv))


def _collect_params(program):
    """Gather the Parameters the recorded graph references (static
    builders create layers inline, so the user has no layer handles —
    upstream's minimize walks the program the same way)."""
    seen, out = set(), []
    for _, arg_specs, _, _ in program._nodes:
        for kind, ref in arg_specs:
            if kind == "param" and id(ref) not in seen:
                seen.add(id(ref))
                out.append(ref)
    return out
