"""Functional higher-order autograd tests (upstream
test/autograd/test_autograd_functional_dynamic.py analogs): jvp/vjp
against finite differences, jacobian/hessian against closed forms."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.autograd import (jvp, vjp, jacobian, hessian,
                                 Jacobian, Hessian)
from paddle_tpu.tensor import Tensor


def _f_scalar(x):
    # f(x) = sum(x^3): grad 3x^2, hessian diag(6x)
    return (x ** 3.0).sum()


def test_jvp_matches_directional_derivative():
    x = Tensor(np.array([1.0, 2.0, 3.0], np.float32))
    v = Tensor(np.array([0.5, -1.0, 2.0], np.float32))
    out, tangent = jvp(_f_scalar, x, v)
    # d/dt f(x + t v) = 3x^2 . v
    expect = float((3 * np.array([1, 4, 9]) *
                    np.array([0.5, -1.0, 2.0])).sum())
    np.testing.assert_allclose(float(tangent.numpy()), expect,
                               rtol=1e-5)
    np.testing.assert_allclose(float(out.numpy()), 36.0, rtol=1e-5)


def test_vjp_matches_gradient():
    x = Tensor(np.array([1.0, 2.0, 3.0], np.float32))
    out, grads = vjp(_f_scalar, x)
    np.testing.assert_allclose(np.asarray(grads.numpy()),
                               3 * np.array([1, 4, 9], np.float32),
                               rtol=1e-5)


def test_vjp_multi_input():
    def f(a, b):
        return (a * b).sum()

    a = Tensor(np.array([1.0, 2.0], np.float32))
    b = Tensor(np.array([3.0, 4.0], np.float32))
    out, grads = vjp(f, [a, b])
    np.testing.assert_allclose(np.asarray(grads[0].numpy()), [3, 4],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads[1].numpy()), [1, 2],
                               rtol=1e-6)


def test_jacobian_linear_map():
    w = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32)

    def f(x):
        return Tensor(w) @ x

    x = Tensor(np.array([1.0, 1.0], np.float32))
    jac = jacobian(f, x)
    np.testing.assert_allclose(np.asarray(jac.numpy()), w, rtol=1e-6)


def test_jacobian_batched():
    def f(x):
        return x ** 2.0

    x = Tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    jac = jacobian(f, x, batch_axis=0)
    # per-example jacobian diag(2x)
    expect = np.stack([np.diag([2.0, 4.0]), np.diag([6.0, 8.0])])
    np.testing.assert_allclose(np.asarray(jac.numpy()), expect,
                               rtol=1e-6)
    with pytest.raises(ValueError, match="batch_axis"):
        jacobian(f, x, batch_axis=1)


def test_hessian_quadratic():
    a = np.array([[2.0, 1.0], [1.0, 4.0]], np.float32)

    def f(x):
        return 0.5 * (x @ (Tensor(a) @ x))

    x = Tensor(np.array([1.0, -1.0], np.float32))
    h = hessian(f, x)
    np.testing.assert_allclose(np.asarray(h.numpy()), a, rtol=1e-5,
                               atol=1e-6)


def test_jacobian_hessian_objects():
    def f(x):
        return (x ** 3.0).sum()

    x = Tensor(np.array([1.0, 2.0], np.float32))
    J = Jacobian(f, x)
    np.testing.assert_allclose(np.asarray(J.tensors.numpy()),
                               [3.0, 12.0], rtol=1e-6)
    np.testing.assert_allclose(float(J[1].numpy()), 12.0, rtol=1e-6)
    H = Hessian(f, x)
    np.testing.assert_allclose(np.asarray(H.tensors.numpy()),
                               np.diag([6.0, 12.0]), rtol=1e-5,
                               atol=1e-6)


def test_incubate_autograd_namespace():
    import paddle_tpu.incubate.autograd as ia
    assert ia.jvp is jvp and ia.Hessian is Hessian
    ia.enable_prim()
    assert ia.prim_enabled()
    ia.disable_prim()


def test_functional_autograd_through_layers():
    """Hessian of a tiny MLP loss — the upstream science/PINN use case
    (forward-over-reverse through real Layers)."""
    paddle.seed(0)
    net = nn.Linear(3, 1)

    def loss(x):
        return (net(x) ** 2.0).sum()

    x = Tensor(np.ones((2, 3), np.float32))
    h = hessian(loss, x)
    # Hessian of sum((xW+b)^2) wrt x is block-diag 2 W W^T per row
    w = np.asarray(net.weight.numpy())           # [3, 1]
    blk = 2.0 * (w @ w.T)                        # [3, 3]
    hv = np.asarray(h.numpy()).reshape(6, 6)
    np.testing.assert_allclose(hv[:3, :3], blk, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hv[3:, 3:], blk, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hv[:3, 3:], 0, atol=1e-6)
