"""HLO collective audit (VERDICT r4 next #3): validate the DCN-bytes
model in DESIGN-DCN.md against the COMPILED program.

The scaling projection rests on two structural claims about the hybrid
train step's collectives:

1. the data-parallel axis (the one that rides DCN across slices)
   carries exactly the gradient all-reduce — per-device bytes
   ~= 4 bytes x (grad elements per device);
2. nothing else spans dp: mp/sep collectives (activation all-reduces,
   ppermute rings) stay on inner-mesh axes, i.e. on ICI.

This test compiles the dp2xmp2 GPT step on the virtual mesh, parses
the partitioned HLO, decodes every collective's replica groups to mesh
axes, and checks both claims quantitatively."""

import re

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.distributed import collective
from paddle_tpu.distributed.runner import DistributedRunner
from paddle_tpu.models import (gpt_tiny, GPTForCausalLM,
                               GPTPretrainingCriterion)

pytestmark = pytest.mark.dist

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                "s32": 4, "u64": 8, "u32": 4, "s8": 1, "u8": 1,
                "pred": 1, "s16": 2, "u16": 2}


def _decode_replica_groups(attr: str, n_dev: int):
    """Decode an HLO replica_groups attribute into a list of device-id
    groups.  Handles both the explicit `{{0,2},{1,3}}` form and the
    iota form `[G,S]<=[d0,d1,...]T(perm)`."""
    attr = attr.strip()
    m = re.match(r"\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", attr)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        x = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            x = x.transpose([int(p) for p in m.group(4).split(",")])
        return x.reshape(g, s).tolist()
    if attr.startswith("{"):
        groups = re.findall(r"\{([\d,\s]+)\}", attr)
        return [[int(v) for v in g.split(",")] for g in groups if g.strip()]
    raise ValueError(f"unparsed replica_groups: {attr!r}")


def _result_bytes(line: str) -> int:
    """Per-device bytes of a collective's result: the shape list
    between ``=`` and the opcode call (partitioned per-device shapes;
    tuple results enumerate every fused operand)."""
    m = re.search(
        r"=\s*(.*?)\s*(?:all-reduce|reduce-scatter|all-gather|"
        r"collective-permute|all-to-all)(?:-start|-done)?\(", line)
    if not m:
        return 0
    total = 0
    for dt, shp in re.findall(r"(\w+)\[([\d,]*)\]", m.group(1)):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in shp.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _axes_spanned(group, coord_of):
    """Mesh axes along which members of a replica group differ."""
    coords = [coord_of[d] for d in group]
    spanned = set()
    for axis in range(len(coords[0])):
        if len({c[axis] for c in coords}) > 1:
            spanned.add(axis)
    return spanned


def test_dp_axis_carries_exactly_the_gradient_allreduce():
    devices = jax.devices()[:4]
    mesh = collective.build_mesh({"dp": 2, "mp": 2}, devices=devices)
    collective.set_mesh(mesh)
    paddle.seed(0)
    net = GPTForCausalLM(gpt_tiny())
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    runner = DistributedRunner(net, opt, GPTPretrainingCriterion(),
                               mesh=mesh)
    rng = np.random.RandomState(0)
    x = rng.randint(0, 256, (8, 32)).astype(np.int64)
    y = np.roll(x, -1, axis=1)
    hlo = runner.lower_step([x], [y]).compile().as_text()

    # partition-id -> (dp, mp) coords, in mesh device order
    mesh_devs = list(mesh.devices.flat)
    axis_names = list(mesh.axis_names)
    dp_axis = axis_names.index("dp")
    coord_of = {}
    for flat_idx, dev in enumerate(mesh_devs):
        coord_of[flat_idx] = np.unravel_index(flat_idx,
                                              mesh.devices.shape)

    dp_ar_bytes = 0
    bad_dp_ops = []
    mp_collectives = 0
    for line in hlo.splitlines():
        if "replica_groups=" not in line:
            continue
        mg = re.search(r"replica_groups=(\{\{[^}]*\}[^)]*\}|\[[^ ]+)",
                       line)
        if not mg:
            continue
        groups = _decode_replica_groups(mg.group(1), len(mesh_devs))
        spanned = _axes_spanned(groups[0], coord_of)
        is_ar = ("all-reduce" in line or "reduce-scatter" in line
                 or "all-gather" in line)
        if dp_axis in spanned:
            if is_ar:
                dp_ar_bytes += _result_bytes(line)
            if "collective-permute" in line or "all-to-all" in line:
                bad_dp_ops.append(line[:120])
        elif spanned:
            mp_collectives += 1

    # claim 2: nothing but (all-)reduce-class traffic spans dp
    assert not bad_dp_ops, \
        f"non-allreduce collectives span the dp axis: {bad_dp_ops}"
    # claim 2b: mp activation collectives exist and stay off dp
    assert mp_collectives > 0, "expected mp-axis activation collectives"

    # claim 1: dp all-reduce bytes ~= 4 bytes x per-device grad elements
    per_dev_elems = 0
    for n, p in runner._name_to_param.items():
        spec = runner._pspecs[n]
        shard = 1
        for ax in spec:
            for name in ([ax] if isinstance(ax, str) else (ax or [])):
                shard *= mesh.shape[name]
        per_dev_elems += int(np.prod(p.shape)) // shard
    expect = 4 * per_dev_elems
    # fused extras (loss/counter scalars, found_inf) are tiny; XLA may
    # also all-reduce a few small f32 buffers twice in epilogues
    assert 0.85 * expect <= dp_ar_bytes <= 1.5 * expect, \
        (f"dp all-reduce bytes {dp_ar_bytes} vs modeled 4*P_chip "
         f"{expect} ({per_dev_elems} per-device grad elements)")


# -- SPMD involuntary-rematerialization pin (ISSUE 11 satellite) ------------


import contextlib


@contextlib.contextmanager
def _cold_compile():
    """Compile with the persistent compilation cache OFF: the remat
    warning is emitted by the SPMD partitioner, which never runs on a
    cache hit — a warm cache would make the warning-free assertion
    vacuously pass and the negative control spuriously fail.  Flipping
    the flag alone is not enough: jax memoizes its is-cache-used
    verdict once per process, so the memo must be reset around the
    flip (and again after, so later tests get their cache back)."""
    from jax._src import compilation_cache as _cc
    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _cc.reset_cache()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        _cc.reset_cache()


def _remat_trigger_runner(sharding_stage=1):
    """The minimal MULTICHIP_r05 warning shape: a trainable leaf whose
    dim 0 does NOT divide the sharding degree (here the [2, 64]
    embedding table), so its ZeRO opt-state/grad sharding falls on an
    INNER dim — exactly the boundary the partitioner used to resolve
    with an involuntary full rematerialization of the batch-sharded
    activation feeding that grad."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as optim
    devices = jax.devices()[:8]
    mesh = collective.build_mesh({"dp": 2, "sharding": 4},
                                 devices=devices)
    collective.set_mesh(mesh)
    paddle.seed(0)
    net = nn.Sequential(nn.Embedding(2, 64), nn.Linear(64, 64))
    opt = optim.Adam(1e-3, parameters=net.parameters())
    runner = DistributedRunner(net, opt, nn.MSELoss(), mesh=mesh,
                               sharding_stage=sharding_stage)
    x = np.zeros((8, 16), dtype=np.int64)
    y = np.random.RandomState(0).rand(8, 16, 64).astype(np.float32)
    return runner, [x], [y]


_REMAT_WARNING = "Involuntary full rematerialization"


def test_zero_grad_boundary_compiles_without_spmd_remat_warnings(
        capfd):
    """MULTICHIP_r05's '[SPMD] Involuntary full rematerialization'
    warnings are dead: the explicit replicated pin on inner-dim-
    sharded grad leaves (runner._constrain_zero_grads) turns the
    partitioner's last-resort remat into a planned reshard.  capfd
    captures XLA's C++ stderr, so the assertion is on the COMPILER's
    own diagnostics."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    for stage in (1, 2):
        runner, x, y = _remat_trigger_runner(stage)
        with _cold_compile():
            runner.lower_step(x, y).compile()
        err = capfd.readouterr().err
        assert _REMAT_WARNING not in err, (stage, err[-2000:])


def test_spmd_remat_detector_still_detects(capfd, monkeypatch):
    """Negative control for the pin above: with the replicated-pin
    boundary annotation disabled (the pre-fix behavior), the SAME
    compile must surface the warning — proving the capture harness
    can actually see it (a silent-capture regression would make the
    warning-free assertion vacuous)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from jax.sharding import NamedSharding
    from paddle_tpu.distributed.fleet.meta_parallel.sharding_parallel \
        import shard_spec_for

    def old_constraint(self, grads, stage, size):
        if stage >= 2:
            return {n: jax.lax.with_sharding_constraint(
                        g, NamedSharding(
                            self.mesh,
                            P(*shard_spec_for(g.shape, size))))
                    for n, g in grads.items()}
        return grads

    from jax.sharding import PartitionSpec as P
    monkeypatch.setattr(DistributedRunner, "_constrain_zero_grads",
                        old_constraint)
    runner, x, y = _remat_trigger_runner(1)
    with _cold_compile():
        runner.lower_step(x, y).compile()
    err = capfd.readouterr().err
    assert _REMAT_WARNING in err, err[-2000:]


# -- compressed-ring bytes audit (ISSUE 11) ---------------------------------


def test_compressed_ring_dp_bytes_match_model():
    """The bytes-moved proxy: on the bits=8 explicit ring, the
    compiled program's dp-spanning collective bytes (every ring hop's
    collective-permute payload) match the analytic
    `dp_comm_bytes_per_step` model within a few percent — int8 wire
    compression is real in the EXECUTABLE, not just the docstring."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    import bench
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.distributed.compressed import dp_comm_bytes_per_step

    mesh = collective.build_mesh({"dp": 2}, devices=jax.devices()[:2])
    collective.set_mesh(mesh)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(256, 512), nn.ReLU(),
                        nn.Linear(512, 64))
    opt = optim.Adam(1e-3, parameters=net.parameters())
    runner = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                               mesh=mesh, dp_compress_bits=8)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 256).astype(np.float32)
    y = rng.randint(0, 64, (16,)).astype(np.int64)
    hlo = runner.lower_step([x], [y]).compile().as_text()
    audited = bench._hlo_dp_collective_bytes(hlo, mesh)
    n_elems = sum(int(np.prod(p.shape)) for p in net.parameters()
                  if not p.stop_gradient)
    modeled = dp_comm_bytes_per_step(n_elems, 2, 8, False)
    assert 0.95 * modeled <= audited <= 1.10 * modeled, \
        (audited, modeled)
