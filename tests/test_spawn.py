"""paddle.distributed.spawn (upstream spawn.py parity): programmatic
multi-process launch with the env contract, rendezvous, and one
cross-process collective."""

import numpy as np
import pytest

pytestmark = pytest.mark.dist


def _worker(tag):
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import paddle_tpu as paddle
    from paddle_tpu.distributed import init_parallel_env

    env = init_parallel_env()
    assert jax.process_count() == 2
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()), ("x",))
    local = jax.device_put(
        np.array([float(env.rank + 1)], np.float32),
        jax.local_devices()[0])
    arr = jax.make_array_from_single_device_arrays(
        (2,), NamedSharding(mesh, P("x")), [local])
    total = float(jax.jit(jnp.sum,
                          out_shardings=NamedSharding(mesh, P()))(arr))
    assert total == 3.0, (tag, total)


def test_spawn_two_ranks_collective():
    from conftest import require_cpu_multiprocess
    require_cpu_multiprocess()
    from paddle_tpu.distributed import spawn
    ctx = spawn(_worker, args=("t1",), nprocs=2, join=True)
    assert all(p.exitcode == 0 for p in ctx.processes)


def test_spawn_propagates_worker_failure():
    from paddle_tpu.distributed import spawn

    with pytest.raises(RuntimeError, match="failed"):
        spawn(_crasher, nprocs=2, join=True)


def _crasher():
    import os
    if os.environ["PADDLE_TRAINER_ID"] == "1":
        raise SystemExit(3)
