"""Chaos suite: deterministic fault injection against the resilience
layer (DESIGN-RESILIENCE.md).

Every recovery path the subsystem claims is exercised here by
*injecting* the failure it handles: KV outages, dropped heartbeats,
hangs, preemption kills, torn checkpoints.  Kept fast (tier-1 runs
them); the process-level scenarios use small subprocesses.
"""

import glob
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, KVClient, KVServer)
from paddle_tpu.distributed.resilience import (
    FailureDetector, FaultPlan, HangWatchdog, InjectedFault,
    RetryExhausted, clear, fault_point, install, retry_call,
    retry_stats, reset_retry_stats, should_drop)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    clear()
    reset_retry_stats()
    yield
    clear()
    reset_retry_stats()


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------
def test_fault_plan_from_json_and_env(tmp_path, monkeypatch):
    plan = FaultPlan.from_json(
        '[{"site":"a","action":"error","at":2,"count":2},'
        ' {"site":"b","action":"drop","match":{"node":"n1"}}]')
    assert len(plan.rules) == 2
    # env: inline JSON
    monkeypatch.setenv("PADDLE_FAULT_PLAN",
                       '[{"site":"x","action":"latency"}]')
    assert FaultPlan.from_env().rules[0].site == "x"
    # env: @file indirection
    p = tmp_path / "plan.json"
    p.write_text('[{"site":"y","action":"crash","exit_code":7}]')
    monkeypatch.setenv("PADDLE_FAULT_PLAN", f"@{p}")
    assert FaultPlan.from_env().rules[0].exit_code == 7
    with pytest.raises(ValueError):
        FaultPlan.from_json('[{"site":"z","bogus_key":1}]')
    with pytest.raises(ValueError):
        FaultPlan.from_json('[{"action":"error"}]')


def test_fault_counting_and_match():
    install(FaultPlan.from_json(
        '[{"site":"s","action":"error","at":2,"count":2}]'))
    fault_point("s")                       # call 1: clean
    for _ in range(2):                     # calls 2,3: injected
        with pytest.raises(InjectedFault):
            fault_point("s")
    fault_point("s")                       # call 4: clean again
    install(FaultPlan.from_json(
        '[{"site":"t","action":"error","match":{"step":3}}]'))
    fault_point("t", step=2)
    with pytest.raises(InjectedFault):
        fault_point("t", step=3)
    fault_point("t", step=4)


def test_once_marker_disarms_across_incarnations(tmp_path):
    """A ``match`` rule with ``once_marker`` fires exactly once even
    across process incarnations (otherwise kill-at-step-N re-kills
    every relaunched run at the same step until the controller's
    restart budget is exhausted)."""
    marker = str(tmp_path / "fired")
    plan_json = ('[{"site":"s","action":"error","match":{"step":3},'
                 f'"once_marker":"{marker}"}}]')
    install(FaultPlan.from_json(plan_json))
    fault_point("s", step=2)
    with pytest.raises(InjectedFault):
        fault_point("s", step=3)
    assert os.path.exists(marker)
    fault_point("s", step=3)               # same process: disarmed
    # fresh incarnation: new injector, same plan — still disarmed
    install(FaultPlan.from_json(plan_json))
    fault_point("s", step=3)


def test_drop_action_via_should_drop():
    install(FaultPlan.from_json(
        '[{"site":"hb","action":"drop","at":1,"count":-1}]'))
    assert should_drop("hb")
    assert should_drop("hb")               # count=-1: forever
    clear()
    assert not should_drop("hb")           # no plan → never drop


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------
def test_retry_succeeds_after_transient_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise ConnectionError("transient")
        return "ok"

    assert retry_call(flaky, max_attempts=5, base_delay=0.001,
                      label="flaky3") == "ok"
    st = retry_stats("flaky3")
    assert st["retries"] == 3 and st["exhausted"] == 0


def test_retry_exhausts_and_chains_cause():
    def dead():
        raise TimeoutError("never up")

    with pytest.raises(RetryExhausted) as ei:
        retry_call(dead, max_attempts=3, base_delay=0.001,
                   label="dead")
    assert isinstance(ei.value.__cause__, TimeoutError)
    assert retry_stats("dead")["exhausted"] == 1


def test_retry_deadline_bounds_total_time():
    def dead():
        raise ConnectionError("down")

    t0 = time.monotonic()
    with pytest.raises(RetryExhausted):
        retry_call(dead, max_attempts=100, base_delay=0.05,
                   max_delay=0.2, deadline=0.4, label="deadline")
    assert time.monotonic() - t0 < 2.0


def test_retry_giveup_fails_fast():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ConnectionError("401-ish")

    with pytest.raises(ConnectionError):
        retry_call(fatal, max_attempts=5, base_delay=0.001,
                   giveup=lambda e: True, label="fatal")
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# KV traffic under injected faults (acceptance: >=3 consecutive
# failures survived without aborting)
# ---------------------------------------------------------------------------
@pytest.fixture
def server():
    s = KVServer(ttl=1.5).start()
    yield s
    s.stop()


def test_kv_survives_3_consecutive_client_faults(server):
    install(FaultPlan.from_json(
        '[{"site":"kv.request","action":"error","at":1,"count":3}]'))
    c = KVClient(server.endpoint)
    c.put("/alive", "yes")                 # 3 injected failures inside
    clear()
    assert c.get("/alive") == "yes"
    st = retry_stats("kv.request")
    assert st["retries"] >= 3 and st["exhausted"] == 0


def test_kv_survives_server_500s(server):
    install(FaultPlan.from_json(
        '[{"site":"kv.server","action":"error","at":1,"count":2}]'))
    c = KVClient(server.endpoint)
    c.put("/k", "v")                       # rides through two 500s
    clear()
    assert c.get("/k") == "v"


def test_kv_injected_latency_is_survived(server):
    install(FaultPlan.from_json(
        '[{"site":"kv.request","action":"latency","latency_s":0.2,'
        '"at":1,"count":1}]'))
    c = KVClient(server.endpoint)
    t0 = time.monotonic()
    c.put("/slow", "1")
    assert time.monotonic() - t0 >= 0.2
    assert c.get("/slow") == "1"


def test_heartbeat_drop_evicts_member_and_detector_sees_loss(server):
    a = ElasticManager(server=server.endpoint, job_id="hd", np="1:3",
                       node_id="node-a", heartbeat_interval=0.2)
    b = ElasticManager(server=server.endpoint, job_id="hd", np="1:3",
                       node_id="node-b", heartbeat_interval=0.2)
    a.register()
    b.register()
    time.sleep(0.4)
    det = a.failure_detector()
    det.poll()
    assert sorted(det.alive()) == ["node-a", "node-b"]
    # from now on node-b's heartbeats are dropped on the wire
    install(FaultPlan.from_json(
        '[{"site":"kv.heartbeat","action":"drop","count":-1,'
        '"match":{"node":"hd/node-b"}}]'))
    deadline = time.time() + 6
    lost = []
    while time.time() < deadline and not lost:
        lost = [e for e in det.poll() if e.kind == "lost"]
        time.sleep(0.2)
    clear()
    assert [e.member for e in lost] == ["node-b"]
    assert det.decide(lost) == "restart"   # still >= np_min
    a.exit()
    b.exit()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_fires_dumps_and_calls_back(tmp_path):
    dump = tmp_path / "hang.txt"
    fired = []
    wd = HangWatchdog(timeout=0.3, on_hang=lambda: fired.append(1),
                      dump_path=str(dump), exit_code=None)
    with wd:
        wd.notify_step(41)
        time.sleep(0.9)
    assert wd.fired and fired == [1]
    text = dump.read_text()
    assert "no training step" in text
    assert "Thread" in text or "thread" in text   # stack dump present
    assert wd.last_step == 41


def test_watchdog_progress_prevents_firing():
    wd = HangWatchdog(timeout=0.5, exit_code=None)
    with wd:
        for _ in range(6):
            time.sleep(0.15)
            wd.notify_step()
    assert not wd.fired


def test_runner_feeds_watchdog_steps():
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.resilience import (current_watchdog,
                                                   install_watchdog)
    from paddle_tpu.distributed.runner import DistributedRunner
    wd = HangWatchdog(timeout=60.0, exit_code=None)
    install_watchdog(wd)   # not started: we only check the feed
    try:
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = optimizer.Adam(1e-2, parameters=net.parameters())
        r = DistributedRunner(net, opt, nn.MSELoss(),
                              mesh=collective.build_mesh({}))
        x = np.random.RandomState(0).rand(4, 4).astype(np.float32)
        y = np.random.RandomState(1).rand(4, 2).astype(np.float32)
        r.train_step([x], [y])
        r.train_step([x], [y])
        assert wd.last_step == 2
        assert current_watchdog() is wd
    finally:
        install_watchdog(None)


# ---------------------------------------------------------------------------
# failure detector
# ---------------------------------------------------------------------------
def test_failure_detector_transitions():
    members = [["a"]]
    fd = FailureDetector(lambda: members[0], np_min=1, grace=0.0)
    assert fd.poll() == []                 # seeding, no events
    members[0] = ["a", "b"]
    evs = fd.poll()
    assert [str(e) for e in evs] == ["joined:b"]
    assert fd.decide(evs) == "restart"
    members[0] = []
    evs = fd.poll()
    assert sorted(e.member for e in evs if e.kind == "lost") == \
        ["a", "b"]
    assert not fd.quorum()
    assert fd.decide(evs) == "hold"


def test_failure_detector_grace_absorbs_one_flap():
    members = [["a", "b"]]
    fd = FailureDetector(lambda: members[0], np_min=1, grace=0.3)
    fd.poll()
    members[0] = ["a"]                     # b misses one poll
    assert fd.poll() == []                 # within grace: suspected
    assert fd.suspects() == ["b"]
    members[0] = ["a", "b"]                # b comes back
    assert fd.poll() == []
    assert fd.suspects() == []
    members[0] = ["a"]                     # b gone for real
    fd.poll()
    time.sleep(0.35)
    evs = fd.poll()
    assert [str(e) for e in evs] == ["lost:b"]


def test_failure_detector_survives_registry_outage():
    state = {"fail": False, "members": ["a", "b"]}

    def members_fn():
        if state["fail"]:
            raise ConnectionError("registry down")
        return state["members"]

    fd = FailureDetector(members_fn, np_min=1)
    fd.poll()
    state["fail"] = True
    assert fd.poll() == []                 # outage ≠ mass eviction
    assert sorted(fd.alive()) == ["a", "b"]


# ---------------------------------------------------------------------------
# verified checkpoints
# ---------------------------------------------------------------------------
class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        return self.fc(x)


def _train1(net, opt, seed):
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.rand(8, 2).astype(np.float32))
    loss = paddle.mse_loss(net(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()


def _corrupt_newest(ckpt_dir, step):
    step_dir = os.path.join(ckpt_dir, str(step))
    files = [p for p in glob.glob(step_dir + "/**", recursive=True)
             if os.path.isfile(p) and "MANIFEST" not in p]
    assert files, f"no data files under {step_dir}"
    victim = max(files, key=os.path.getsize)
    with open(victim, "r+b") as f:
        f.truncate(max(0, os.path.getsize(victim) // 2))
    return victim


def test_manifest_written_on_commit_and_verified(tmp_path):
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(str(tmp_path / "c"),
                           async_save=False) as mgr:
        for step in (1, 2):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
        assert mgr.verified_steps() == [1, 2]
        assert mgr.latest_verified_step() == 2
        man = os.path.join(str(tmp_path / "c"), "2",
                           "RESILIENCE_MANIFEST.json")
        meta = json.load(open(man))
        assert meta["step"] == 2 and meta["files"]


def test_restore_scans_past_torn_newest(tmp_path):
    """Acceptance: a truncated newest checkpoint dir must not crash
    restore — it falls back to the latest verified step."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    weights = {}
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2, 3):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
            weights[step] = np.asarray(net.fc.weight.numpy()).copy()
    _corrupt_newest(d, 3)
    net2 = _Net()
    opt2 = optimizer.Adam(1e-2, parameters=net2.parameters())
    with CheckpointManager(d, async_save=False) as mgr2:
        assert mgr2.verified_steps() == [1, 2]
        with pytest.warns(UserWarning, match="verification"):
            step = mgr2.restore(net2, opt2)
        # the torn dir is quarantined (bytes kept, step namespace
        # freed so the resumed run can re-save step 3)
        assert mgr2.all_steps() == [1, 2]
    assert step == 2
    assert os.path.isdir(os.path.join(d, "_quarantined", "3"))
    assert not os.path.exists(os.path.join(d, "3"))
    np.testing.assert_allclose(np.asarray(net2.fc.weight.numpy()),
                               weights[2], rtol=1e-6)


def test_legacy_manifestless_checkpoints_restore_and_survive(tmp_path):
    """A pre-resilience checkpoint dir (no manifests anywhere) must
    still restore (legacy newest-first) and must NEVER be purged."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
    # strip the manifests → looks exactly like an upgrade-in-place
    for man in glob.glob(d + "/*/RESILIENCE_MANIFEST.json"):
        os.remove(man)
    net2 = _Net()
    with CheckpointManager(d, async_save=False) as mgr2:
        with pytest.warns(UserWarning, match="pre-resilience"):
            assert mgr2.restore(net2) == 2
        assert mgr2.all_steps() == [1, 2]   # nothing deleted


def test_mixed_legacy_and_corrupt_restores_legacy(tmp_path):
    """Upgrade mid-training: older manifest-less steps + a torn
    manifested newest.  Restore must fall back to the newest legacy
    step (warned) and quarantine the torn dir — not return 0 and not
    leave a wedge."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2, 3):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
    for s in (1, 2):   # steps 1-2 predate the manifest format
        os.remove(os.path.join(d, str(s), "RESILIENCE_MANIFEST.json"))
    _corrupt_newest(d, 3)
    net2 = _Net()
    with CheckpointManager(d, async_save=False) as mgr2:
        with pytest.warns(UserWarning, match="manifest-less"):
            assert mgr2.restore(net2) == 2
        assert mgr2.all_steps() == [1, 2]   # torn step 3 quarantined
    assert os.path.isdir(os.path.join(d, "_quarantined", "3"))


def test_transient_restore_failure_never_purges(tmp_path):
    """An outage while reading (injected IO errors on every restore)
    must leave every on-disk step intact — purging is reserved for
    bytes that contradict their own manifest."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
    install(FaultPlan.from_json(
        '[{"site":"checkpoint.restore","action":"error",'
        '"at":1,"count":-1}]'))
    net2 = _Net()
    with CheckpointManager(d, async_save=False) as mgr2:
        with pytest.warns(UserWarning, match="falling back"):
            assert mgr2.restore(net2) == 0    # outage: nothing restored
        clear()
        assert mgr2.all_steps() == [1, 2]     # ...and nothing destroyed
        assert mgr2.restore(net2) == 2        # recovers once IO is back


def test_sigterm_during_inflight_save_is_deferred(tmp_path):
    """A SIGTERM landing while orbax is mid-save must not re-enter the
    (non-reentrant) manager from the handler; it is deferred and runs
    as soon as the interrupted save unwinds."""
    import signal
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(str(tmp_path / "c"), async_save=False)
    try:
        mgr.save_on_preemption(lambda: 99, net, opt)
        handler = signal.getsignal(signal.SIGTERM)
        mgr._in_save = True              # simulate mid-save interrupt
        handler(signal.SIGTERM, None)    # must defer, not save/exit
        assert mgr._deferred_sigterm is not None
        assert mgr.all_steps() == []
        mgr._in_save = False
        with pytest.raises(SystemExit):  # deferred preemption runs now
            mgr.save(1, net, opt, force=True)
        assert 99 in mgr.all_steps()     # the preemption ckpt landed
    finally:
        mgr.uninstall_preemption_handler()
        mgr._mgr.close()


def test_async_rolling_manifest_flush(tmp_path):
    """Async mode must not hold every manifest hostage until
    close(): by the time save(N) returns, steps < N are committed and
    manifested — otherwise a SIGKILL rolls the next restore back past
    the whole incarnation."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(d, async_save=True)
    for step in (1, 2, 3):
        _train1(net, opt, step)
        mgr.save(step, net, opt, force=True)
    # no wait_until_finished/close yet: steps 1 and 2 must already
    # carry manifests on disk (only step 3 may still be pending)
    for s in (1, 2):
        assert os.path.exists(os.path.join(
            d, str(s), "RESILIENCE_MANIFEST.json")), s
    mgr.close()
    assert mgr.verify_step(3)


def test_cross_thread_force_save_routes_sync(tmp_path):
    """ROADMAP resilience follow-up: orbax requires all ASYNC saves to
    be issued from ONE thread.  A save arriving on another thread —
    the watchdog's on_hang force-save — while the owner thread has an
    async save in flight must take the synchronous side-manager path
    instead of tripping orbax's cross-thread finalize assert."""
    import threading
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(d, async_save=True)
    errs = []
    mgr.save(1, net, opt, force=True)      # async, possibly in flight

    def other_thread_save():
        try:
            mgr.save(2, net, opt, force=True)
        except Exception as e:             # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=other_thread_save)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive() and not errs, errs
    assert mgr.cross_thread_syncs == 1
    # the sync save is committed AND manifested when save() returns
    assert mgr.verify_step(2)
    mgr.wait_until_finished()
    assert set(mgr.verified_steps()) >= {1, 2}
    mgr.close()
    # a fresh manager (relaunch) restores the watchdog's step
    with CheckpointManager(d, async_save=True) as mgr2:
        assert mgr2.restore(net, opt) == 2


def test_watchdog_on_hang_force_save_is_safe(tmp_path):
    """End-to-end: HangWatchdog fires on ITS thread mid-async-save
    traffic; the on_hang force-save lands, verified, without touching
    the owner thread's orbax manager."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(d, async_save=True)
    mgr.save(1, net, opt, force=True)
    saved = []
    wd = HangWatchdog(
        timeout=0.3, exit_code=None,
        on_hang=lambda: saved.append(
            mgr.save(7, net, opt, force=True)))
    with wd:
        wd.notify_step(1)
        time.sleep(0.9)                    # let it fire
    assert wd.fired and saved == [True]
    assert mgr.cross_thread_syncs == 1
    assert mgr.verify_step(7)
    assert mgr.latest_verified_step() == 7
    mgr.close()


def test_sigterm_handler_restored_on_close():
    import signal
    prev = signal.getsignal(signal.SIGTERM)
    paddle.seed(0)
    net = _Net()
    import tempfile
    with CheckpointManager(tempfile.mkdtemp(),
                           async_save=False) as mgr:
        mgr.save_on_preemption(lambda: 0, net)
        assert signal.getsignal(signal.SIGTERM) is not prev
    assert signal.getsignal(signal.SIGTERM) is prev


_CRASH_COMMIT_BODY = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)
        def forward(self, x):
            return self.fc(x)

    paddle.seed(0)
    net = Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(os.environ["CKPT_DIR"], async_save=False)
    rng = np.random.RandomState(0)
    for step in (1, 2):
        x = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
        y = paddle.to_tensor(rng.rand(8, 2).astype(np.float32))
        loss = paddle.mse_loss(net(x), y)
        loss.backward(); opt.step(); opt.clear_grad()
        mgr.save(step, net, opt, force=True)   # crash fires at step 2
    print("UNREACHABLE")
""")


def test_crash_mid_commit_leaves_step_unverified(tmp_path):
    """A preemption between data-commit and manifest write must leave
    the step invisible to the verified scan (torn-commit semantics)."""
    ckpt = str(tmp_path / "c")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["CKPT_DIR"] = ckpt
    env["PADDLE_FAULT_PLAN"] = (
        '[{"site":"checkpoint.commit","action":"crash",'
        '"match":{"step":2},"exit_code":143}]')
    script = tmp_path / "crash_commit.py"
    script.write_text(_CRASH_COMMIT_BODY)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode == 143, (proc.returncode, proc.stderr)
    assert "UNREACHABLE" not in proc.stdout
    mgr = CheckpointManager(ckpt, async_save=False)
    # step 1 committed+verified; step 2's data may exist but has no
    # manifest → the verified scan must not trust it
    assert mgr.latest_verified_step() == 1
    net = _Net()
    assert mgr.restore(net) == 1
    mgr.close()


# ---------------------------------------------------------------------------
# static retry coverage (CI-less enforcement: the checker runs as a
# plain test, so tier-1 fails if a bare urlopen/checkpoint-IO call
# sneaks in outside the retry layer)
# ---------------------------------------------------------------------------
def test_static_retry_coverage():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_retry_coverage
        violations = check_retry_coverage.check()
    finally:
        sys.path.pop(0)
    assert not violations, "\n".join(
        f"paddle_tpu/{rel}:{line}: {msg}"
        for rel, line, msg in violations)


# ---------------------------------------------------------------------------
# chaos end-to-end (acceptance): LeNet, kill-at-step-N, torn newest
# checkpoint, auto-resume, identical final loss
# ---------------------------------------------------------------------------
_LENET_WORKER = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.distributed.runner import DistributedRunner

    TOTAL = 5
    paddle.seed(7)
    net = LeNet()
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=net.parameters())
    mgr = CheckpointManager(os.environ["CKPT_DIR"], async_save=False)
    start = mgr.restore(net, opt)   # verified scan: skips torn dirs
    runner = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                               mesh=collective.build_mesh({}))
    runner.set_global_step(start)   # per-step RNG keys stay aligned
    if start:
        print(f"RESUMED-FROM {start}", flush=True)
    final = None
    for step in range(start + 1, TOTAL + 1):
        rng = np.random.RandomState(1000 + step)
        x = rng.rand(8, 1, 28, 28).astype(np.float32)
        y = rng.randint(0, 10, (8,)).astype(np.int64)
        # the kill-at-step fault fires inside train_step, after the
        # step commits but BEFORE this step's checkpoint is written —
        # exactly the window a preemption hits in production
        final = float(runner.train_step([x], [y]))
        mgr.save(step, net, opt, force=True)
    mgr.close()
    with open(os.environ["LOSS_OUT"], "w") as f:
        f.write(f"{final:.9e}")
    print("TRAIN-COMPLETE", flush=True)
""")


def _run_lenet(tmp_path, name, ckpt_dir, fault_plan=None, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # fixed single-device topology for bit-identical runs
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env["CKPT_DIR"] = ckpt_dir
    env["LOSS_OUT"] = str(tmp_path / f"{name}.loss")
    env.pop("PADDLE_FAULT_PLAN", None)
    if fault_plan:
        env["PADDLE_FAULT_PLAN"] = fault_plan
    script = tmp_path / "lenet_worker.py"
    script.write_text(_LENET_WORKER)
    return subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


@pytest.mark.dist
def test_chaos_e2e_kill_torn_checkpoint_resume_identical_loss(
        tmp_path):
    """The acceptance scenario end-to-end:

    1. fault-free LeNet run → reference final loss;
    2. same run with an injected kill at train step 3 (preemption
       window: after the step, before its checkpoint) → dies with the
       plan's exit code, checkpoints exist through step 2;
    3. the newest surviving checkpoint dir is torn (truncated file);
    4. relaunch: restore scans back to the latest *verified* step,
       training resumes and finishes with a final loss identical to
       the uninterrupted run.
    """
    # 1. reference
    p = _run_lenet(tmp_path, "ref", str(tmp_path / "ckpt_ref"))
    assert p.returncode == 0, p.stderr[-2000:]
    ref = float((tmp_path / "ref.loss").read_text())

    # 2. kill at step 3
    ckpt = str(tmp_path / "ckpt_chaos")
    plan = ('[{"site":"train.step","action":"crash",'
            '"match":{"step":3},"exit_code":143}]')
    p = _run_lenet(tmp_path, "killed", ckpt, fault_plan=plan)
    assert p.returncode == 143, (p.returncode, p.stderr[-2000:])
    assert "TRAIN-COMPLETE" not in p.stdout
    assert not (tmp_path / "killed.loss").exists()

    # 3. tear the newest surviving checkpoint (step 2)
    mgr = CheckpointManager(ckpt, async_save=False)
    steps = mgr.all_steps()
    mgr.close()
    assert steps and max(steps) == 2, steps
    _corrupt_newest(ckpt, 2)

    # 4. resume — must fall back to step 1 and still converge to the
    # identical final loss
    p = _run_lenet(tmp_path, "resumed", ckpt)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "RESUMED-FROM 1" in p.stdout, p.stdout
    assert "TRAIN-COMPLETE" in p.stdout
    resumed = float((tmp_path / "resumed.loss").read_text())
    np.testing.assert_allclose(resumed, ref, rtol=0, atol=0)
