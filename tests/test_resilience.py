"""Chaos suite: deterministic fault injection against the resilience
layer (DESIGN-RESILIENCE.md).

Every recovery path the subsystem claims is exercised here by
*injecting* the failure it handles: KV outages, dropped heartbeats,
hangs, preemption kills, torn checkpoints.  Kept fast (tier-1 runs
them); the process-level scenarios use small subprocesses.
"""

import glob
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, KVClient, KVServer)
from paddle_tpu.distributed.resilience import (
    FailureDetector, FaultPlan, HangWatchdog, InjectedFault,
    RetryExhausted, clear, fault_point, install, retry_call,
    retry_stats, reset_retry_stats, should_drop)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    clear()
    reset_retry_stats()
    yield
    clear()
    reset_retry_stats()


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------
def test_fault_plan_from_json_and_env(tmp_path, monkeypatch):
    plan = FaultPlan.from_json(
        '[{"site":"a","action":"error","at":2,"count":2},'
        ' {"site":"b","action":"drop","match":{"node":"n1"}}]')
    assert len(plan.rules) == 2
    # env: inline JSON
    monkeypatch.setenv("PADDLE_FAULT_PLAN",
                       '[{"site":"x","action":"latency"}]')
    assert FaultPlan.from_env().rules[0].site == "x"
    # env: @file indirection
    p = tmp_path / "plan.json"
    p.write_text('[{"site":"y","action":"crash","exit_code":7}]')
    monkeypatch.setenv("PADDLE_FAULT_PLAN", f"@{p}")
    assert FaultPlan.from_env().rules[0].exit_code == 7
    with pytest.raises(ValueError):
        FaultPlan.from_json('[{"site":"z","bogus_key":1}]')
    with pytest.raises(ValueError):
        FaultPlan.from_json('[{"action":"error"}]')


def test_fault_counting_and_match():
    install(FaultPlan.from_json(
        '[{"site":"s","action":"error","at":2,"count":2}]'))
    fault_point("s")                       # call 1: clean
    for _ in range(2):                     # calls 2,3: injected
        with pytest.raises(InjectedFault):
            fault_point("s")
    fault_point("s")                       # call 4: clean again
    install(FaultPlan.from_json(
        '[{"site":"t","action":"error","match":{"step":3}}]'))
    fault_point("t", step=2)
    with pytest.raises(InjectedFault):
        fault_point("t", step=3)
    fault_point("t", step=4)


def test_once_marker_disarms_across_incarnations(tmp_path):
    """A ``match`` rule with ``once_marker`` fires exactly once even
    across process incarnations (otherwise kill-at-step-N re-kills
    every relaunched run at the same step until the controller's
    restart budget is exhausted)."""
    marker = str(tmp_path / "fired")
    plan_json = ('[{"site":"s","action":"error","match":{"step":3},'
                 f'"once_marker":"{marker}"}}]')
    install(FaultPlan.from_json(plan_json))
    fault_point("s", step=2)
    with pytest.raises(InjectedFault):
        fault_point("s", step=3)
    assert os.path.exists(marker)
    fault_point("s", step=3)               # same process: disarmed
    # fresh incarnation: new injector, same plan — still disarmed
    install(FaultPlan.from_json(plan_json))
    fault_point("s", step=3)


def test_drop_action_via_should_drop():
    install(FaultPlan.from_json(
        '[{"site":"hb","action":"drop","at":1,"count":-1}]'))
    assert should_drop("hb")
    assert should_drop("hb")               # count=-1: forever
    clear()
    assert not should_drop("hb")           # no plan → never drop


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------
def test_retry_succeeds_after_transient_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise ConnectionError("transient")
        return "ok"

    assert retry_call(flaky, max_attempts=5, base_delay=0.001,
                      label="flaky3") == "ok"
    st = retry_stats("flaky3")
    assert st["retries"] == 3 and st["exhausted"] == 0


def test_retry_exhausts_and_chains_cause():
    def dead():
        raise TimeoutError("never up")

    with pytest.raises(RetryExhausted) as ei:
        retry_call(dead, max_attempts=3, base_delay=0.001,
                   label="dead")
    assert isinstance(ei.value.__cause__, TimeoutError)
    assert retry_stats("dead")["exhausted"] == 1


def test_retry_deadline_bounds_total_time():
    def dead():
        raise ConnectionError("down")

    t0 = time.monotonic()
    with pytest.raises(RetryExhausted):
        retry_call(dead, max_attempts=100, base_delay=0.05,
                   max_delay=0.2, deadline=0.4, label="deadline")
    assert time.monotonic() - t0 < 2.0


def test_retry_giveup_fails_fast():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ConnectionError("401-ish")

    with pytest.raises(ConnectionError):
        retry_call(fatal, max_attempts=5, base_delay=0.001,
                   giveup=lambda e: True, label="fatal")
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# KV traffic under injected faults (acceptance: >=3 consecutive
# failures survived without aborting)
# ---------------------------------------------------------------------------
@pytest.fixture
def server():
    s = KVServer(ttl=1.5).start()
    yield s
    s.stop()


def test_kv_survives_3_consecutive_client_faults(server):
    install(FaultPlan.from_json(
        '[{"site":"kv.request","action":"error","at":1,"count":3}]'))
    c = KVClient(server.endpoint)
    c.put("/alive", "yes")                 # 3 injected failures inside
    clear()
    assert c.get("/alive") == "yes"
    st = retry_stats("kv.request")
    assert st["retries"] >= 3 and st["exhausted"] == 0


def test_kv_survives_server_500s(server):
    install(FaultPlan.from_json(
        '[{"site":"kv.server","action":"error","at":1,"count":2}]'))
    c = KVClient(server.endpoint)
    c.put("/k", "v")                       # rides through two 500s
    clear()
    assert c.get("/k") == "v"


def test_kv_injected_latency_is_survived(server):
    install(FaultPlan.from_json(
        '[{"site":"kv.request","action":"latency","latency_s":0.2,'
        '"at":1,"count":1}]'))
    c = KVClient(server.endpoint)
    t0 = time.monotonic()
    c.put("/slow", "1")
    assert time.monotonic() - t0 >= 0.2
    assert c.get("/slow") == "1"


def test_heartbeat_drop_evicts_member_and_detector_sees_loss(server):
    a = ElasticManager(server=server.endpoint, job_id="hd", np="1:3",
                       node_id="node-a", heartbeat_interval=0.2)
    b = ElasticManager(server=server.endpoint, job_id="hd", np="1:3",
                       node_id="node-b", heartbeat_interval=0.2)
    a.register()
    b.register()
    time.sleep(0.4)
    det = a.failure_detector()
    det.poll()
    assert sorted(det.alive()) == ["node-a", "node-b"]
    # from now on node-b's heartbeats are dropped on the wire
    install(FaultPlan.from_json(
        '[{"site":"kv.heartbeat","action":"drop","count":-1,'
        '"match":{"node":"hd/node-b"}}]'))
    deadline = time.time() + 6
    lost = []
    while time.time() < deadline and not lost:
        lost = [e for e in det.poll() if e.kind == "lost"]
        time.sleep(0.2)
    clear()
    assert [e.member for e in lost] == ["node-b"]
    assert det.decide(lost) == "restart"   # still >= np_min
    a.exit()
    b.exit()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_fires_dumps_and_calls_back(tmp_path):
    dump = tmp_path / "hang.txt"
    fired = []
    wd = HangWatchdog(timeout=0.3, on_hang=lambda: fired.append(1),
                      dump_path=str(dump), exit_code=None)
    with wd:
        wd.notify_step(41)
        time.sleep(0.9)
    assert wd.fired and fired == [1]
    text = dump.read_text()
    assert "no training step" in text
    assert "Thread" in text or "thread" in text   # stack dump present
    assert wd.last_step == 41


def test_watchdog_progress_prevents_firing():
    wd = HangWatchdog(timeout=0.5, exit_code=None)
    with wd:
        for _ in range(6):
            time.sleep(0.15)
            wd.notify_step()
    assert not wd.fired


def test_runner_feeds_watchdog_steps():
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.resilience import (current_watchdog,
                                                   install_watchdog)
    from paddle_tpu.distributed.runner import DistributedRunner
    wd = HangWatchdog(timeout=60.0, exit_code=None)
    install_watchdog(wd)   # not started: we only check the feed
    try:
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = optimizer.Adam(1e-2, parameters=net.parameters())
        r = DistributedRunner(net, opt, nn.MSELoss(),
                              mesh=collective.build_mesh({}))
        x = np.random.RandomState(0).rand(4, 4).astype(np.float32)
        y = np.random.RandomState(1).rand(4, 2).astype(np.float32)
        r.train_step([x], [y])
        r.train_step([x], [y])
        assert wd.last_step == 2
        assert current_watchdog() is wd
    finally:
        install_watchdog(None)


# ---------------------------------------------------------------------------
# failure detector
# ---------------------------------------------------------------------------
def test_failure_detector_transitions():
    members = [["a"]]
    fd = FailureDetector(lambda: members[0], np_min=1, grace=0.0)
    assert fd.poll() == []                 # seeding, no events
    members[0] = ["a", "b"]
    evs = fd.poll()
    assert [str(e) for e in evs] == ["joined:b"]
    assert fd.decide(evs) == "restart"
    members[0] = []
    evs = fd.poll()
    assert sorted(e.member for e in evs if e.kind == "lost") == \
        ["a", "b"]
    assert not fd.quorum()
    assert fd.decide(evs) == "hold"


def test_failure_detector_grace_absorbs_one_flap():
    members = [["a", "b"]]
    fd = FailureDetector(lambda: members[0], np_min=1, grace=0.3)
    fd.poll()
    members[0] = ["a"]                     # b misses one poll
    assert fd.poll() == []                 # within grace: suspected
    assert fd.suspects() == ["b"]
    members[0] = ["a", "b"]                # b comes back
    assert fd.poll() == []
    assert fd.suspects() == []
    members[0] = ["a"]                     # b gone for real
    fd.poll()
    time.sleep(0.35)
    evs = fd.poll()
    assert [str(e) for e in evs] == ["lost:b"]


def test_failure_detector_survives_registry_outage():
    state = {"fail": False, "members": ["a", "b"]}

    def members_fn():
        if state["fail"]:
            raise ConnectionError("registry down")
        return state["members"]

    fd = FailureDetector(members_fn, np_min=1)
    fd.poll()
    state["fail"] = True
    assert fd.poll() == []                 # outage ≠ mass eviction
    assert sorted(fd.alive()) == ["a", "b"]


# ---------------------------------------------------------------------------
# verified checkpoints
# ---------------------------------------------------------------------------
class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        return self.fc(x)


def _train1(net, opt, seed):
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.rand(8, 2).astype(np.float32))
    loss = paddle.mse_loss(net(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()


def _corrupt_newest(ckpt_dir, step):
    step_dir = os.path.join(ckpt_dir, str(step))
    files = [p for p in glob.glob(step_dir + "/**", recursive=True)
             if os.path.isfile(p) and "MANIFEST" not in p]
    assert files, f"no data files under {step_dir}"
    victim = max(files, key=os.path.getsize)
    with open(victim, "r+b") as f:
        f.truncate(max(0, os.path.getsize(victim) // 2))
    return victim


def test_manifest_written_on_commit_and_verified(tmp_path):
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(str(tmp_path / "c"),
                           async_save=False) as mgr:
        for step in (1, 2):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
        assert mgr.verified_steps() == [1, 2]
        assert mgr.latest_verified_step() == 2
        man = os.path.join(str(tmp_path / "c"), "2",
                           "RESILIENCE_MANIFEST.json")
        meta = json.load(open(man))
        assert meta["step"] == 2 and meta["files"]


def test_restore_scans_past_torn_newest(tmp_path):
    """Acceptance: a truncated newest checkpoint dir must not crash
    restore — it falls back to the latest verified step."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    weights = {}
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2, 3):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
            weights[step] = np.asarray(net.fc.weight.numpy()).copy()
    _corrupt_newest(d, 3)
    net2 = _Net()
    opt2 = optimizer.Adam(1e-2, parameters=net2.parameters())
    with CheckpointManager(d, async_save=False) as mgr2:
        assert mgr2.verified_steps() == [1, 2]
        with pytest.warns(UserWarning, match="verification"):
            step = mgr2.restore(net2, opt2)
        # the torn dir is quarantined (bytes kept, step namespace
        # freed so the resumed run can re-save step 3)
        assert mgr2.all_steps() == [1, 2]
    assert step == 2
    assert os.path.isdir(os.path.join(d, "_quarantined", "3"))
    assert not os.path.exists(os.path.join(d, "3"))
    np.testing.assert_allclose(np.asarray(net2.fc.weight.numpy()),
                               weights[2], rtol=1e-6)


def test_legacy_manifestless_checkpoints_restore_and_survive(tmp_path):
    """A pre-resilience checkpoint dir (no manifests anywhere) must
    still restore (legacy newest-first) and must NEVER be purged."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
    # strip the manifests → looks exactly like an upgrade-in-place
    for man in glob.glob(d + "/*/RESILIENCE_MANIFEST.json"):
        os.remove(man)
    net2 = _Net()
    with CheckpointManager(d, async_save=False) as mgr2:
        with pytest.warns(UserWarning, match="pre-resilience"):
            assert mgr2.restore(net2) == 2
        assert mgr2.all_steps() == [1, 2]   # nothing deleted


def test_mixed_legacy_and_corrupt_restores_legacy(tmp_path):
    """Upgrade mid-training: older manifest-less steps + a torn
    manifested newest.  Restore must fall back to the newest legacy
    step (warned) and quarantine the torn dir — not return 0 and not
    leave a wedge."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2, 3):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
    for s in (1, 2):   # steps 1-2 predate the manifest format
        os.remove(os.path.join(d, str(s), "RESILIENCE_MANIFEST.json"))
    _corrupt_newest(d, 3)
    net2 = _Net()
    with CheckpointManager(d, async_save=False) as mgr2:
        with pytest.warns(UserWarning, match="manifest-less"):
            assert mgr2.restore(net2) == 2
        assert mgr2.all_steps() == [1, 2]   # torn step 3 quarantined
    assert os.path.isdir(os.path.join(d, "_quarantined", "3"))


def test_transient_restore_failure_never_purges(tmp_path):
    """An outage while reading (injected IO errors on every restore)
    must leave every on-disk step intact — purging is reserved for
    bytes that contradict their own manifest."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
    install(FaultPlan.from_json(
        '[{"site":"checkpoint.restore","action":"error",'
        '"at":1,"count":-1}]'))
    net2 = _Net()
    with CheckpointManager(d, async_save=False) as mgr2:
        with pytest.warns(UserWarning, match="falling back"):
            assert mgr2.restore(net2) == 0    # outage: nothing restored
        clear()
        assert mgr2.all_steps() == [1, 2]     # ...and nothing destroyed
        assert mgr2.restore(net2) == 2        # recovers once IO is back


def test_sigterm_during_inflight_save_is_deferred(tmp_path):
    """A SIGTERM landing while orbax is mid-save must not re-enter the
    (non-reentrant) manager from the handler; it is deferred and runs
    as soon as the interrupted save unwinds."""
    import signal
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(str(tmp_path / "c"), async_save=False)
    try:
        mgr.save_on_preemption(lambda: 99, net, opt)
        handler = signal.getsignal(signal.SIGTERM)
        mgr._in_save = True              # simulate mid-save interrupt
        handler(signal.SIGTERM, None)    # must defer, not save/exit
        assert mgr._deferred_sigterm is not None
        assert mgr.all_steps() == []
        mgr._in_save = False
        with pytest.raises(SystemExit):  # deferred preemption runs now
            mgr.save(1, net, opt, force=True)
        assert 99 in mgr.all_steps()     # the preemption ckpt landed
    finally:
        mgr.uninstall_preemption_handler()
        mgr._mgr.close()


def test_async_rolling_manifest_flush(tmp_path):
    """Async mode must not hold every manifest hostage until
    close(): by the time save(N) returns, steps < N are committed and
    manifested — otherwise a SIGKILL rolls the next restore back past
    the whole incarnation."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(d, async_save=True)
    for step in (1, 2, 3):
        _train1(net, opt, step)
        mgr.save(step, net, opt, force=True)
    # no wait_until_finished/close yet: steps 1 and 2 must already
    # carry manifests on disk (only step 3 may still be pending)
    for s in (1, 2):
        assert os.path.exists(os.path.join(
            d, str(s), "RESILIENCE_MANIFEST.json")), s
    mgr.close()
    assert mgr.verify_step(3)


def test_cross_thread_force_save_routes_sync(tmp_path):
    """ROADMAP resilience follow-up: orbax requires all ASYNC saves to
    be issued from ONE thread.  A save arriving on another thread —
    the watchdog's on_hang force-save — while the owner thread has an
    async save in flight must take the synchronous side-manager path
    instead of tripping orbax's cross-thread finalize assert."""
    import threading
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(d, async_save=True)
    errs = []
    mgr.save(1, net, opt, force=True)      # async, possibly in flight

    def other_thread_save():
        try:
            mgr.save(2, net, opt, force=True)
        except Exception as e:             # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=other_thread_save)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive() and not errs, errs
    assert mgr.cross_thread_syncs == 1
    # the sync save is committed AND manifested when save() returns
    assert mgr.verify_step(2)
    mgr.wait_until_finished()
    assert set(mgr.verified_steps()) >= {1, 2}
    mgr.close()
    # a fresh manager (relaunch) restores the watchdog's step
    with CheckpointManager(d, async_save=True) as mgr2:
        assert mgr2.restore(net, opt) == 2


def test_watchdog_on_hang_force_save_is_safe(tmp_path):
    """End-to-end: HangWatchdog fires on ITS thread mid-async-save
    traffic; the on_hang force-save lands, verified, without touching
    the owner thread's orbax manager."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(d, async_save=True)
    mgr.save(1, net, opt, force=True)
    saved = []
    wd = HangWatchdog(
        timeout=0.3, exit_code=None,
        on_hang=lambda: saved.append(
            mgr.save(7, net, opt, force=True)))
    with wd:
        wd.notify_step(1)
        time.sleep(0.9)                    # let it fire
    assert wd.fired and saved == [True]
    assert mgr.cross_thread_syncs == 1
    assert mgr.verify_step(7)
    assert mgr.latest_verified_step() == 7
    mgr.close()


def test_sigterm_handler_restored_on_close():
    import signal
    prev = signal.getsignal(signal.SIGTERM)
    paddle.seed(0)
    net = _Net()
    import tempfile
    with CheckpointManager(tempfile.mkdtemp(),
                           async_save=False) as mgr:
        mgr.save_on_preemption(lambda: 0, net)
        assert signal.getsignal(signal.SIGTERM) is not prev
    assert signal.getsignal(signal.SIGTERM) is prev


_CRASH_COMMIT_BODY = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)
        def forward(self, x):
            return self.fc(x)

    paddle.seed(0)
    net = Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    mgr = CheckpointManager(os.environ["CKPT_DIR"], async_save=False)
    rng = np.random.RandomState(0)
    for step in (1, 2):
        x = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
        y = paddle.to_tensor(rng.rand(8, 2).astype(np.float32))
        loss = paddle.mse_loss(net(x), y)
        loss.backward(); opt.step(); opt.clear_grad()
        mgr.save(step, net, opt, force=True)   # crash fires at step 2
    print("UNREACHABLE")
""")


def test_crash_mid_commit_leaves_step_unverified(tmp_path):
    """A preemption between data-commit and manifest write must leave
    the step invisible to the verified scan (torn-commit semantics)."""
    ckpt = str(tmp_path / "c")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["CKPT_DIR"] = ckpt
    env["PADDLE_FAULT_PLAN"] = (
        '[{"site":"checkpoint.commit","action":"crash",'
        '"match":{"step":2},"exit_code":143}]')
    script = tmp_path / "crash_commit.py"
    script.write_text(_CRASH_COMMIT_BODY)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode == 143, (proc.returncode, proc.stderr)
    assert "UNREACHABLE" not in proc.stdout
    mgr = CheckpointManager(ckpt, async_save=False)
    # step 1 committed+verified; step 2's data may exist but has no
    # manifest → the verified scan must not trust it
    assert mgr.latest_verified_step() == 1
    net = _Net()
    assert mgr.restore(net) == 1
    mgr.close()


# the static retry-coverage check now lives in tests/test_analysis.py
# (ISSUE 17: one parametrized module runs every pass on one shared
# parse)


# ---------------------------------------------------------------------------
# chaos end-to-end (acceptance): kill-at-step-N, torn newest
# checkpoint, auto-resume, identical final loss
# ---------------------------------------------------------------------------
_LENET_WORKER = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.distributed.runner import DistributedRunner

    # a small MLP classifier: the resilience semantics under test
    # (kill-at-step, torn checkpoint, quarantine, RNG-aligned
    # bit-identical resume) are architecture-independent, and the MLP
    # compiles in a fraction of LeNet's conv-stack time — this e2e
    # spawns three training processes, so compile time triples
    # (conv bit-parity itself stays pinned in-process by
    # test_step_folding's LeNet parity test)
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.flat = nn.Flatten()
            self.fc1 = nn.Linear(784, 32)
            self.fc2 = nn.Linear(32, 10)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return self.fc2(F.relu(self.fc1(self.flat(x))))

    TOTAL = 5
    paddle.seed(7)
    net = Net()
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=net.parameters())
    mgr = CheckpointManager(os.environ["CKPT_DIR"], async_save=False)
    start = mgr.restore(net, opt)   # verified scan: skips torn dirs
    runner = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                               mesh=collective.build_mesh({}))
    runner.set_global_step(start)   # per-step RNG keys stay aligned
    if start:
        print(f"RESUMED-FROM {start}", flush=True)
    final = None
    for step in range(start + 1, TOTAL + 1):
        rng = np.random.RandomState(1000 + step)
        x = rng.rand(8, 1, 28, 28).astype(np.float32)
        y = rng.randint(0, 10, (8,)).astype(np.int64)
        # the kill-at-step fault fires inside train_step, after the
        # step commits but BEFORE this step's checkpoint is written —
        # exactly the window a preemption hits in production
        final = float(runner.train_step([x], [y]))
        mgr.save(step, net, opt, force=True)
    mgr.close()
    with open(os.environ["LOSS_OUT"], "w") as f:
        f.write(f"{final:.9e}")
    print("TRAIN-COMPLETE", flush=True)
""")


def _run_lenet(tmp_path, name, ckpt_dir, fault_plan=None, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # fixed single-device topology for bit-identical runs
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env["CKPT_DIR"] = ckpt_dir
    env["LOSS_OUT"] = str(tmp_path / f"{name}.loss")
    env.pop("PADDLE_FAULT_PLAN", None)
    if fault_plan:
        env["PADDLE_FAULT_PLAN"] = fault_plan
    script = tmp_path / "lenet_worker.py"
    script.write_text(_LENET_WORKER)
    return subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


@pytest.mark.dist
def test_chaos_e2e_kill_torn_checkpoint_resume_identical_loss(
        tmp_path):
    """The acceptance scenario end-to-end:

    1. fault-free run → reference final loss;
    2. same run with an injected kill at train step 3 (preemption
       window: after the step, before its checkpoint) → dies with the
       plan's exit code, checkpoints exist through step 2;
    3. the newest surviving checkpoint dir is torn (truncated file);
    4. relaunch: restore scans back to the latest *verified* step,
       training resumes and finishes with a final loss identical to
       the uninterrupted run.
    """
    # 1. reference
    p = _run_lenet(tmp_path, "ref", str(tmp_path / "ckpt_ref"))
    assert p.returncode == 0, p.stderr[-2000:]
    ref = float((tmp_path / "ref.loss").read_text())

    # 2. kill at step 3
    ckpt = str(tmp_path / "ckpt_chaos")
    plan = ('[{"site":"train.step","action":"crash",'
            '"match":{"step":3},"exit_code":143}]')
    p = _run_lenet(tmp_path, "killed", ckpt, fault_plan=plan)
    assert p.returncode == 143, (p.returncode, p.stderr[-2000:])
    assert "TRAIN-COMPLETE" not in p.stdout
    assert not (tmp_path / "killed.loss").exists()

    # 3. tear the newest surviving checkpoint (step 2)
    mgr = CheckpointManager(ckpt, async_save=False)
    steps = mgr.all_steps()
    mgr.close()
    assert steps and max(steps) == 2, steps
    _corrupt_newest(ckpt, 2)

    # 4. resume — must fall back to step 1 and still converge to the
    # identical final loss
    p = _run_lenet(tmp_path, "resumed", ckpt)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "RESUMED-FROM 1" in p.stdout, p.stdout
    assert "TRAIN-COMPLETE" in p.stdout
    resumed = float((tmp_path / "resumed.loss").read_text())
    np.testing.assert_allclose(resumed, ref, rtol=0, atol=0)


# the static fault-site registry check now lives in
# tests/test_analysis.py (ISSUE 17)


# ---------------------------------------------------------------------------
# beacon monitor (data-plane liveness cross-check)
# ---------------------------------------------------------------------------
def test_beacon_monitor_stall_and_recovery():
    from paddle_tpu.distributed.resilience import BeaconMonitor
    bm = BeaconMonitor(timeout=1.0)
    bm.observe("r0", '{"beat": 1}', now=0.0)
    bm.observe("r1", '{"beat": 1}', now=0.0)
    # r0 progresses, r1 freezes
    bm.observe("r0", '{"beat": 2}', now=0.9)
    bm.observe("r1", '{"beat": 1}', now=0.9)
    assert bm.stalled(now=1.5) == ["r1"]
    assert bm.lag("r0", now=1.5) == pytest.approx(0.6)
    assert bm.lag("r1", now=1.5) == pytest.approx(1.5)
    # a member that never published is never judged
    bm.observe("r2", None, now=1.5)
    assert "r2" not in bm.stalled(now=99.0)
    # recovery: the frozen value moves again
    bm.observe("r0", '{"beat": 3}', now=1.6)
    bm.observe("r1", '{"beat": 2}', now=1.6)
    assert bm.stalled(now=2.0) == []
    # quarantined member drops out of judgment
    bm.forget("r1")
    assert bm.lag("r1") is None


def test_beacon_publish_drop_rule_freezes_value(server):
    """The chaos model of a wedged chip: heartbeat alive (separate
    thread), beacon publishes dropped on the wire — the monitor must
    see the value freeze."""
    from paddle_tpu.distributed.resilience import BeaconMonitor
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    ctx = ElasticRankContext(server.endpoint, "bd", "rank-0",
                             rank=0, heartbeat_interval=0.2)
    ctx.register()
    bm = BeaconMonitor(timeout=0.5)
    key = "/k/bd/beacon/0"
    assert ctx.publish_beacon(step=1)
    v1 = ctx.client.get(key)
    assert v1 is not None
    bm.observe("rank-0", v1)
    # wedge: every further publish is dropped
    install(FaultPlan.from_json(
        '[{"site":"beacon.publish","action":"drop","count":-1,'
        '"match":{"member":"rank-0"}}]'))
    assert not ctx.publish_beacon(step=2)
    assert ctx.client.get(key) == v1          # value frozen
    time.sleep(0.6)
    bm.observe("rank-0", ctx.client.get(key))
    assert bm.stalled() == ["rank-0"]
    # ...while the control-plane heartbeat stayed alive the whole time
    assert "bd/rank-0" in ctx.client.members("bd/")
    clear()
    ctx.exit()


def test_elastic_rank_context_from_env(server, monkeypatch):
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    monkeypatch.delenv("PADDLE_ELASTIC_SERVER", raising=False)
    monkeypatch.delenv("PADDLE_MEMBER_ID", raising=False)
    assert ElasticRankContext.from_env() is None
    monkeypatch.setenv("PADDLE_ELASTIC_SERVER", server.endpoint)
    monkeypatch.setenv("PADDLE_MEMBER_ID", "rank-1")
    monkeypatch.setenv("PADDLE_JOB_ID", "fe")
    monkeypatch.setenv("PADDLE_RANK_ROLE", "rank")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    ctx = ElasticRankContext.from_env()
    assert ctx is not None and ctx.rank == 1 and ctx.role == "rank"
    monkeypatch.setenv("PADDLE_RANK_ROLE", "spare")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "-1")
    monkeypatch.setenv("PADDLE_MEMBER_ID", "spare-0")
    sp = ElasticRankContext.from_env()
    assert sp.rank is None and sp.role == "spare"


def test_promotion_ticket_wait_and_shutdown(server):
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext, PromotionTicket)
    ctx = ElasticRankContext(server.endpoint, "pt", "spare-0",
                             role="spare", poll_interval=0.02)
    # no ticket, no shutdown → timeout returns None
    assert ctx.wait_for_promotion(timeout=0.2) is None
    ctx.client.put("/k/pt/promote/spare-0",
                   PromotionTicket(rank=1, epoch=3).to_json())
    t = ctx.wait_for_promotion(timeout=5)
    assert t == PromotionTicket(rank=1, epoch=3)
    assert ctx.rank == 1 and ctx.role == "rank"
    # shutdown key releases a parked spare
    ctx2 = ElasticRankContext(server.endpoint, "pt", "spare-1",
                              role="spare", poll_interval=0.02)
    ctx.client.put("/k/pt/shutdown", "1")
    assert ctx2.wait_for_promotion(timeout=5) is None


def test_reform_barrier_agrees_on_min_and_is_injectable(server):
    """Two members meet at the reform barrier, each proposing its own
    newest restorable step; both must compute the SAME resume point
    (the min) — and the ``barrier.reform`` site must be deterministic
    chaos surface."""
    import threading
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    a = ElasticRankContext(server.endpoint, "rb", "rank-0", rank=0,
                           poll_interval=0.02)
    b = ElasticRankContext(server.endpoint, "rb", "spare-0", rank=1,
                           poll_interval=0.02)
    out = {}

    def run(ctx, name, propose):
        out[name] = ctx.reform_barrier(1, [0, 1], propose, timeout=10)

    t = threading.Thread(target=run, args=(b, "b", 2))
    t.start()
    run(a, "a", 3)
    t.join(timeout=10)
    assert out == {"a": 2, "b": 2}            # min(3, 2)
    # injection: an error rule on barrier.reform fires on entry
    install(FaultPlan.from_json(
        '[{"site":"barrier.reform","action":"error","at":1,'
        '"count":1}]'))
    with pytest.raises(InjectedFault):
        a.reform_barrier(2, [0], 3, timeout=1)
    clear()


def test_reform_barrier_range_aware_clamps_to_retention(server):
    """Range-aware proposals (ISSUE 14 satellite): the barrier
    validates min(newest) against every member's retention window —
    a feasible window returns min(newest) exactly as before; an empty
    window (a fast rank's retention already evicted the agreed step)
    raises ReformWindowError identically on every member instead of
    letting a rollback fail mid-reform (the PR-13 drain-e2e cascade)."""
    import threading
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext, ReformWindowError)
    a = ElasticRankContext(server.endpoint, "rbw", "rank-0", rank=0,
                           poll_interval=0.02)
    b = ElasticRankContext(server.endpoint, "rbw", "rank-1", rank=1,
                           poll_interval=0.02)
    out, errs = {}, {}

    def run(ctx, name, epoch, propose, oldest):
        try:
            out[name] = ctx.reform_barrier(epoch, [0, 1], propose,
                                           oldest_step=oldest,
                                           timeout=10)
        except Exception as e:                      # noqa: BLE001
            errs[name] = e

    # feasible: windows [2, 9] and [3, 5] → resume min(9, 5) = 5 >= 3
    t = threading.Thread(target=run, args=(b, "b", 1, 5, 3))
    t.start()
    run(a, "a", 1, 9, 2)
    t.join(timeout=10)
    assert out == {"a": 5, "b": 5} and not errs
    # empty: slow member's newest (5) is below the fast member's
    # oldest (36) → BOTH members fail with the same loud verdict
    out.clear()
    t = threading.Thread(target=run, args=(b, "b", 2, 5, 1))
    t.start()
    run(a, "a", 2, 40, 36)
    t.join(timeout=10)
    assert not out
    assert isinstance(errs["a"], ReformWindowError)
    assert isinstance(errs["b"], ReformWindowError)
    assert "retention window" in str(errs["a"])
    # resume == 0 (a member proposes a fresh start) stays feasible
    # regardless of windows: step 0 is re-initialization, not a
    # checkpoint read
    out.clear()
    errs.clear()
    t = threading.Thread(target=run, args=(b, "b", 3, 0, 0))
    t.start()
    run(a, "a", 3, 40, 36)
    t.join(timeout=10)
    assert out == {"a": 0, "b": 0} and not errs


def test_reform_barrier_legacy_peer_has_unbounded_window(server):
    """A pre-range peer (no "oldest" in its barrier record) must be
    treated as unbounded-below — mixed fleets keep re-forming."""
    import json as _json
    import threading
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    a = ElasticRankContext(server.endpoint, "rbl", "rank-0", rank=0,
                           poll_interval=0.02)
    # hand-write rank 1's arrival in the legacy (rangeless) format
    a.client.put("/k/rbl/barrier/1/1",
                 _json.dumps({"propose": 4, "member": "rank-1"}))
    assert a.reform_barrier(1, [0, 1], 7, oldest_step=2,
                            timeout=10) == 4


def test_oldest_verified_step_tracks_retention(tmp_path):
    """CheckpointManager.oldest_verified_step — the lower edge of the
    reform-proposal window — follows max_to_keep eviction."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    paddle.seed(0)
    net = nn.Linear(4, 4)
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=net.parameters())
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False,
                            max_to_keep=2)
    assert mgr.oldest_verified_step() is None
    for s in (1, 2, 3):
        assert mgr.save(s, net, opt, force=True)
    assert mgr.oldest_verified_step() == 2      # step 1 evicted
    assert mgr.latest_verified_step() == 3
    mgr.close()


def test_step_barrier_detects_epoch_bump(server):
    """A member parked in the data-plane lockstep barrier must notice
    a membership epoch bump and hand control to the reform path
    instead of waiting forever for a dead peer."""
    import json as _json
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    ctx = ElasticRankContext(server.endpoint, "sb", "rank-0", rank=0,
                             poll_interval=0.02)
    ctx.client.put("/k/sb/epoch", _json.dumps(
        {"epoch": 0, "members": {"0": "rank-0", "1": "rank-1"}}))

    def bump():
        time.sleep(0.3)
        ctx.client.put("/k/sb/epoch", _json.dumps(
            {"epoch": 1, "members": {"0": "rank-0", "1": "spare-0"}}))

    import threading
    t = threading.Thread(target=bump)
    t.start()
    rec = ctx.step_barrier(4, epoch=0, timeout=10)
    t.join()
    assert rec is not None and rec["epoch"] == 1
    assert rec["members"]["1"] == "spare-0"
    # with all members arrived, the barrier passes (returns None)
    ctx.client.put("/k/sb/steps/5/0", "{}")
    ctx.client.put("/k/sb/steps/5/1", "{}")
    assert ctx.step_barrier(5, epoch=1, timeout=10) is None


# ---------------------------------------------------------------------------
# controller promotion path (in-process, stub processes): the real
# _queue_failure/_try_promote code against a real KV registry, with
# the member.promote site chaos-injected
# ---------------------------------------------------------------------------
class _StubProc:
    def __init__(self, rc=None):
        self._rc = rc
        self.killed = False

    def poll(self):
        return self._rc

    def kill(self):
        self.killed = True
        self._rc = -9

    def send_signal(self, sig):
        self._rc = -int(sig)


def _stub_controller(server, job_id="ctl"):
    import types
    from paddle_tpu.distributed.fleet.elastic import KVClient
    from paddle_tpu.distributed.launch.controller import (
        RankController, _Member)
    args = types.SimpleNamespace(job_id=job_id, log_dir="/tmp",
                                 training_script="x.py",
                                 training_script_args=[])
    ctl = RankController(args, KVClient(server.endpoint),
                         server.endpoint, nproc=2, spares=1,
                         beacon_timeout=0.5)
    ctl.state.members = {
        0: _Member("rank-0", _StubProc(), "", rank=0),
        1: _Member("rank-1", _StubProc(), "", rank=1)}
    ctl.state.spares = [_Member("spare-0", _StubProc(), "", rank=None)]
    ctl._publish_epoch()
    return ctl


def test_controller_promotes_spare_and_is_injectable(server):
    import json as _json
    ctl = _stub_controller(server)
    prom0 = ctl._promotions.collect()
    quar0 = ctl._quarantines.collect()
    dead = ctl.state.members[1]
    # the promotion path itself is chaos surface: first attempt is
    # injected to fail; the rank stays queued and the retry succeeds
    install(FaultPlan.from_json(
        '[{"site":"member.promote","action":"error","at":1,'
        '"count":1}]'))
    ctl._queue_failure(1, "exit rc=143")
    assert dead.quarantined and dead.proc.killed
    assert ctl.state.pending_failures == [1]
    assert ctl._try_promote(1) is False       # injected failure
    assert ctl.state.members[1] is dead       # membership unchanged
    assert ctl._try_promote(1) is True        # retry lands
    clear()
    assert ctl.state.members[1].member_id == "spare-0"
    assert ctl.state.spares == []
    assert ctl.state.epoch == 1
    # ticket + epoch record visible to workers, under the per-launch
    # run-id namespace (stale-state isolation on reused registries)
    from paddle_tpu.distributed.resilience.elastic_rank import kv_key
    assert ctl.run_id
    ticket = _json.loads(ctl.client.get(
        kv_key("ctl", "promote", "spare-0", run_id=ctl.run_id)))
    assert ticket == {"rank": 1, "epoch": 1}
    rec = _json.loads(ctl.client.get(
        kv_key("ctl", "epoch", run_id=ctl.run_id)))
    assert rec["epoch"] == 1
    assert rec["members"] == {"0": "rank-0", "1": "spare-0"}
    # observability: promotion/quarantine counters ticked
    assert ctl._promotions.collect() == prom0 + 1
    assert ctl._quarantines.collect() == quar0 + 1


def test_controller_no_spare_left_reports_failure(server):
    ctl = _stub_controller(server, job_id="ctl2")
    ctl.state.spares = []
    ctl._queue_failure(0, "exit rc=1")
    assert ctl._try_promote(0) is False


def test_controller_respawns_spare_after_promotion(server):
    """ISSUE 10 satellite (ROADMAP PR-9 follow-up): a successful
    promotion respawns a replacement spare with a FRESH member id, so
    the pool no longer drains to zero; the live pool is exported as
    ``resilience_spares_available``."""
    from paddle_tpu.distributed.launch.controller import _Member
    ctl = _stub_controller(server, job_id="ctl-respawn")
    spawned = []
    ctl._endpoints = ["127.0.0.1:1", "127.0.0.1:2"]
    ctl._master = server.endpoint

    def fake_spawn(member_id, role, rank, endpoints, master, log_name):
        spawned.append((member_id, role, rank))
        return _Member(member_id, _StubProc(), "", rank=rank)

    ctl._spawn = fake_spawn
    assert ctl._spares_gauge.collect() == 1.0     # initial pool
    ctl._queue_failure(1, "exit rc=1")
    assert ctl._try_promote(1) is True
    # spare-0 was promoted; a replacement with a fresh id (its
    # predecessor's promotion-ticket key must never be reused) joined
    # the pool
    assert spawned == [("spare-1", "spare", None)]
    assert [s.member_id for s in ctl.state.spares] == ["spare-1"]
    assert ctl.state.members[1].member_id == "spare-0"
    # a second failure is survivable with the replenished pool
    ctl._queue_failure(0, "exit rc=1")
    assert ctl._try_promote(0) is True
    assert ctl.state.members[0].member_id == "spare-1"
    assert [s.member_id for s in ctl.state.spares] == ["spare-2"]


def test_controller_respawn_can_be_disabled_and_survives_failure(
        server):
    from paddle_tpu.distributed.launch.controller import _Member
    ctl = _stub_controller(server, job_id="ctl-norespawn")
    ctl.respawn_spares = False
    ctl._endpoints = ["127.0.0.1:1", "127.0.0.1:2"]
    spawned = []
    ctl._spawn = lambda *a, **kw: spawned.append(a)
    ctl._queue_failure(1, "exit rc=1")
    assert ctl._try_promote(1) is True
    assert spawned == [] and ctl.state.spares == []
    # respawn failure is reported, never fatal (pool stays short)
    ctl2 = _stub_controller(server, job_id="ctl-failspawn")
    ctl2._endpoints = ["127.0.0.1:1", "127.0.0.1:2"]

    def bad_spawn(*a, **kw):
        raise OSError("fork failed")

    ctl2._spawn = bad_spawn
    ctl2._queue_failure(0, "exit rc=1")
    assert ctl2._try_promote(0) is True
    assert ctl2.state.spares == []


# ---------------------------------------------------------------------------
# multi-host supervision (ISSUE 18): the HostAgent command protocol
# (idempotent cmd/<seq> records) and the controller's node-level
# failure domain (lease judgment, batch promotion under ONE epoch)
# ---------------------------------------------------------------------------
def _stub_agent(server, tmp_path, host_id="h0", job_id="aj",
                run_id="r1"):
    import types
    from paddle_tpu.distributed.launch.agent import HostAgent
    args = types.SimpleNamespace(job_id=job_id,
                                 log_dir=str(tmp_path))
    agent = HostAgent(args, KVClient(server.endpoint), host_id)
    agent.run_id = run_id     # adopted (normally from the run record)
    spawned = []

    def fake_popen(cmd, env, log_path):
        spawned.append((list(cmd), dict(env), log_path))
        proc = _StubProc()
        proc.pid = 4242 + len(spawned)
        return proc

    agent._popen = fake_popen
    return agent, spawned


def test_agent_commands_are_idempotent_and_retry_on_injection(
        server, tmp_path):
    """THE idempotency pin: a command record consumed twice — by a
    retry after an injected ``agent.command`` failure, or by a fresh
    agent incarnation re-walking the sequence — never double-spawns,
    because the ack record is checked before executing."""
    import json as _json
    from paddle_tpu.distributed.resilience.elastic_rank import kv_key
    agent, spawned = _stub_agent(server, tmp_path)
    key = lambda *p: kv_key("aj", *p, run_id="r1")  # noqa: E731
    agent.client.put(key("agent", "h0", "cmd", "0"), _json.dumps(
        {"op": "spawn", "seq": 0, "member": "rank-0", "role": "rank",
         "rank": 0, "env": {"PADDLE_TRAINER_ID": "0"},
         "script": "train.py", "args": ["--x"],
         "log_name": "workerlog.0"}))
    agent._consume_commands()
    assert len(spawned) == 1
    cmd, env, log_path = spawned[0]
    assert cmd[1:] == ["train.py", "--x"]
    assert env["PADDLE_TRAINER_ID"] == "0"
    # per-host log subtree: two simulated agents must never share one
    assert os.path.join(str(tmp_path), "h0") in log_path
    ack = _json.loads(agent.client.get(key("agent", "h0", "ack", "0")))
    assert ack == {"seq": 0, "ok": True, "error": None}
    # a restarted agent re-walks from seq 0: the ack gate skips the
    # executed command — no second spawn
    agent2, spawned2 = _stub_agent(server, tmp_path)
    agent2._consume_commands()
    assert spawned2 == [] and agent2._next_seq == 1
    # injected agent.command failure: the command stays UNACKED and
    # the next tick retries it — executed exactly once overall
    agent2.client.put(key("agent", "h0", "cmd", "1"), _json.dumps(
        {"op": "spawn", "seq": 1, "member": "spare-0",
         "role": "spare", "rank": None, "env": {},
         "script": "train.py", "args": [],
         "log_name": "sparelog.0"}))
    install(FaultPlan.from_json(
        '[{"site":"agent.command","action":"error","at":1,'
        '"count":1}]'))
    agent2._consume_commands()
    assert spawned2 == []
    assert agent2.client.get(key("agent", "h0", "ack", "1")) is None
    agent2._consume_commands()      # retry lands
    clear()
    assert len(spawned2) == 1 and spawned2[0][2].endswith("sparelog.0")
    assert _json.loads(agent2.client.get(
        key("agent", "h0", "ack", "1")))["ok"] is True


def test_agent_spawn_failure_acks_false_with_synthetic_rc(
        server, tmp_path):
    """A spawn whose fork fails DID execute: it must ack (retrying a
    half-run spawn is how double-spawns happen) and report a
    synthetic nonzero rc in the lease, so the controller judges it
    through the ordinary exit-rc path — a worker that never spawned
    also never heartbeats, which no liveness detector can see."""
    import json as _json
    from paddle_tpu.distributed.resilience.elastic_rank import kv_key
    agent, _ = _stub_agent(server, tmp_path, job_id="aj2")

    def bad_popen(cmd, env, log_path):
        raise OSError("fork failed")

    agent._popen = bad_popen
    key = lambda *p: kv_key("aj2", *p, run_id="r1")  # noqa: E731
    agent.client.put(key("agent", "h0", "cmd", "0"), _json.dumps(
        {"op": "spawn", "seq": 0, "member": "rank-1", "role": "rank",
         "rank": 1, "env": {}, "script": "t.py", "args": [],
         "log_name": "workerlog.1"}))
    agent._consume_commands()
    ack = _json.loads(agent.client.get(key("agent", "h0", "ack", "0")))
    assert ack["ok"] is False and "fork failed" in ack["error"]
    agent._refresh_lease()
    lease = _json.loads(agent.client.get(key("node", "h0")))
    assert lease["procs"]["rank-1"]["rc"] == 127


def _remote_controller(server, tmp_path, job_id="mh"):
    import types
    from paddle_tpu.distributed.launch.controller import (
        RankController, _Member, _RemoteProc)
    args = types.SimpleNamespace(job_id=job_id,
                                 log_dir=str(tmp_path),
                                 training_script="x.py",
                                 training_script_args=[])
    ctl = RankController(args, KVClient(server.endpoint),
                         server.endpoint, nproc=2, spares=2,
                         beacon_timeout=30.0, nnodes=2)
    ctl.hosts = ["h0", "h1"]
    ctl._host_ips = {"h0": "127.0.0.1", "h1": "127.0.0.1"}
    ctl._endpoints = [f"127.0.0.1:{9000 + r}" for r in range(4)]
    ctl._master = server.endpoint

    def member(mid, rank, host):
        return _Member(mid, _RemoteProc(ctl, host, mid), "",
                       rank=rank, host=host)

    ctl.state.members = {r: member(f"rank-{r}", r,
                                   "h0" if r < 2 else "h1")
                         for r in range(4)}
    # spares round-robin across nodes, like _run_remote lays them out
    ctl.state.spares = [member(f"spare-{j}", None,
                               "h0" if j % 2 == 0 else "h1")
                        for j in range(4)]
    ctl._spare_seq = 4
    ctl._publish_epoch()
    return ctl


def test_controller_node_death_batch_promotes_under_one_epoch(
        server, tmp_path):
    """Node-level failure domain: a frozen lease is judged NODE DEATH
    — every rank the host held quarantined in one pass, and the whole
    batch promoted under a SINGLE epoch bump (an intermediate epoch
    naming a still-dead member would hang the survivors' reform
    barrier).  Replacement spares respawn on the surviving host."""
    import json as _json
    from paddle_tpu.distributed.resilience.elastic_rank import kv_key
    ctl = _remote_controller(server, tmp_path)
    deaths0 = ctl._node_deaths.collect()
    t0 = time.monotonic()
    lease = lambda beat: _json.dumps(  # noqa: E731
        {"beat": beat, "pid": 1, "parked": False, "procs": {}})
    ctl.client.put(ctl._kv_key("node", "h0"), lease(0))
    ctl.client.put(ctl._kv_key("node", "h1"), lease(0))
    ctl._judge_nodes(now=t0)
    assert ctl._dead_hosts == set()
    # h0 keeps beating, h1's lease freezes past the timeout
    ctl.client.put(ctl._kv_key("node", "h0"), lease(1))
    ctl._judge_nodes(now=t0 + ctl.node_lease_timeout)
    ctl._judge_nodes(now=t0 + ctl.node_lease_timeout + 0.5)
    assert ctl._dead_hosts == {"h1"}
    assert ctl._node_deaths.collect() == deaths0 + 1
    # ALL of h1's processes are dead with it (ranks AND spares): the
    # synthesized rc makes every liveness predicate agree
    for mid in ("rank-2", "rank-3", "spare-1", "spare-3"):
        assert ctl._remote_rc[mid] == -9
    assert ctl.state.pending_failures == [2, 3]
    assert ctl.state.members[2].quarantined
    assert ctl.state.members[3].quarantined
    # node gauges: 1 alive / 1 dead; the dead host's lease-age series
    # ended with it (absent, not stale)
    assert ctl._reg.gauge("fleet_nodes",
                          labels={"state": "alive"}).collect() == 1.0
    assert ctl._reg.gauge("fleet_nodes",
                          labels={"state": "dead"}).collect() == 1.0
    # the epoch record published while the batch is pending EXCLUDES
    # the quarantined members — survivors must never be parked at a
    # barrier a dead member can't join
    ctl._publish_epoch()
    rec = _json.loads(ctl.client.get(
        kv_key("mh", "epoch", run_id=ctl.run_id)))
    assert set(rec["members"]) == {"0", "1"}
    # batch promotion: both ranks land under ONE epoch bump, tickets
    # both name epoch 1, and the pool refills on the SURVIVING host
    assert ctl._promote_batch(list(ctl.state.pending_failures)) == \
        [2, 3]
    assert ctl.state.epoch == 1
    assert ctl.state.members[2].member_id == "spare-0"
    assert ctl.state.members[3].member_id == "spare-2"
    for spare, rank in (("spare-0", 2), ("spare-2", 3)):
        ticket = _json.loads(ctl.client.get(
            kv_key("mh", "promote", spare, run_id=ctl.run_id)))
        assert ticket == {"rank": rank, "epoch": 1}
    rec = _json.loads(ctl.client.get(
        kv_key("mh", "epoch", run_id=ctl.run_id)))
    assert rec["epoch"] == 1
    assert rec["members"] == {"0": "rank-0", "1": "rank-1",
                              "2": "spare-0", "3": "spare-2"}
    respawned = [s for s in ctl.state.spares
                 if s.member_id in ("spare-4", "spare-5")]
    assert [s.host for s in respawned] == ["h0", "h0"]
    # the healthz node section shows the degraded fleet at one glance
    h = ctl._fleet_health_summary()
    nodes = {n["host"]: n for n in h["nodes"]}
    assert nodes["h1"]["alive"] is False
    assert nodes["h0"]["ranks"] == [0, 1, 2, 3]
    assert h["status"] == "degraded"


def test_controller_partial_batch_keeps_uncovered_rank_queued(
        server, tmp_path):
    """A spare pool that covers a node death only partially promotes
    what it can: the covered ranks land under one epoch bump, the
    uncovered rank stays queued (retried when the pool refills), and
    the published epoch names no dead member."""
    import json as _json
    from paddle_tpu.distributed.resilience.elastic_rank import kv_key
    ctl = _remote_controller(server, tmp_path, job_id="mh2")
    ctl.respawn_spares = False
    # only ONE live spare survives: spare-0 on h0
    ctl.state.spares = ctl.state.spares[:1]
    t0 = time.monotonic()
    lease = lambda beat: _json.dumps(  # noqa: E731
        {"beat": beat, "pid": 1, "parked": False, "procs": {}})
    ctl.client.put(ctl._kv_key("node", "h0"), lease(0))
    ctl.client.put(ctl._kv_key("node", "h1"), lease(0))
    ctl._judge_nodes(now=t0)
    ctl.client.put(ctl._kv_key("node", "h0"), lease(1))
    ctl._judge_nodes(now=t0 + ctl.node_lease_timeout + 0.5)
    assert ctl.state.pending_failures == [2, 3]
    assert ctl._promote_batch(list(ctl.state.pending_failures)) == [2]
    assert ctl.state.epoch == 1
    rec = _json.loads(ctl.client.get(
        kv_key("mh2", "epoch", run_id=ctl.run_id)))
    # rank 3 is still down: the epoch record must NOT name it
    assert rec["members"] == {"0": "rank-0", "1": "rank-1",
                              "2": "spare-0"}


def test_controller_straggler_gauge_fires_on_injected_latency(
        server, capsys):
    """ISSUE 10: the controller turns the beacon records it already
    polls into per-rank step-time; a rank lagging the fleet median
    beyond the factor raises ``fleet_straggler{rank=…}`` on the
    controller registry plus a log line, and recovery clears it."""
    import json as _json
    ctl = _stub_controller(server, job_id="ctl-straggler")
    t0 = time.monotonic()
    # rank 0 steps every 0.1s, rank 1 every 0.5s (injected latency)
    for i in range(8):
        ctl.client.put(ctl._kv_key("beacon", "0"),
                       _json.dumps({"beat": i, "step": i}))
        ctl.client.put(ctl._kv_key("beacon", "1"),
                       _json.dumps({"beat": i, "step": i}))
        ctl.straggler.observe(0, i, now=t0 + i * 0.1)
        ctl.straggler.observe(1, i, now=t0 + i * 0.5)
    ctl._poll_beacons()          # the production feed path runs too
    ctl._judge_stragglers()
    reg = ctl._reg
    assert reg.gauge("fleet_straggler",
                     labels={"rank": "1"}).collect() == 1.0
    assert reg.gauge("fleet_straggler",
                     labels={"rank": "0"}).collect() == 0.0
    assert reg.gauge("fleet_rank_step_time_s",
                     labels={"rank": "1"}).collect() > \
        2 * reg.gauge("fleet_rank_step_time_s",
                      labels={"rank": "0"}).collect()
    err = capsys.readouterr().err
    assert "straggler: rank 1" in err
    # recovery: the lagging rank speeds back up -> flag drops (and
    # the log line does not repeat while flagged)
    for i in range(8, 30):
        ctl.straggler.observe(1, i, now=t0 + 4.0 + (i - 8) * 0.1)
        ctl.straggler.observe(0, i, now=t0 + 4.0 + (i - 8) * 0.1)
    ctl._judge_stragglers()
    assert reg.gauge("fleet_straggler",
                     labels={"rank": "1"}).collect() == 0.0
    # a LIVE rank whose estimate window expires (parked at a
    # barrier/checkpoint) scrapes ABSENT, not frozen at the last
    # verdict — drain the window to simulate expiry (the test's
    # synthetic timestamps sit in the future, so shrinking window_s
    # cannot age them out)
    saved_points = dict(ctl.straggler._points)
    ctl.straggler._points.clear()
    ctl._judge_stragglers()
    from paddle_tpu.observability import export as _oe
    snap_now = _oe.snapshot(materialize=False)
    assert 'fleet_straggler{rank="0"}' not in snap_now
    assert 'fleet_straggler{rank="1"}' not in snap_now
    ctl.straggler._points.update(saved_points)   # estimates return
    ctl._judge_stragglers()
    # quarantine clears BOTH the window and the exported series — a
    # promoted successor must not inherit its predecessor's verdict
    # (absent until it earns its own, not stale)
    from paddle_tpu.observability import export as obs_export
    ctl._queue_failure(1, "exit rc=1")
    snap = obs_export.snapshot(materialize=False)
    assert 'fleet_straggler{rank="1"}' not in snap
    assert 'fleet_rank_step_time_s{rank="1"}' not in snap
    assert 'fleet_straggler{rank="0"}' in snap


def test_controller_beacon_poll_feeds_monitor(server):
    ctl = _stub_controller(server, job_id="ctl3")
    ctl.beacons.timeout = 0.3
    ctl.client.put(ctl._kv_key("beacon", "0"), '{"beat": 1}')
    ctl.client.put(ctl._kv_key("beacon", "1"), '{"beat": 1}')
    ctl._poll_beacons()
    time.sleep(0.2)
    ctl.client.put(ctl._kv_key("beacon", "0"), '{"beat": 2}')  # 0 moves
    ctl._poll_beacons()
    time.sleep(0.2)
    ctl._poll_beacons()
    assert ctl.beacons.stalled() == ["rank-1"]
    # finished ranks drop out of judgment (they stop beaconing by
    # design) — the watch loop forgets them on clean exit
    ctl.beacons.forget("rank-1")
    assert ctl.beacons.stalled() == []


# ---------------------------------------------------------------------------
# straggler auto-drain policy (ISSUE 13 §Action loop): N-consecutive-
# window hysteresis, off-by-default, no-spare refusal, chaos
# injectability, verdict forgotten on quarantine
# ---------------------------------------------------------------------------
def _feed_straggler_windows(ctl, slow=0.5, fast=0.1, steps=8):
    """Synthetic beacon timeline: rank 0 steps every ``fast`` s,
    rank 1 every ``slow`` s (same shape as the PR-10 straggler unit
    test)."""
    t0 = time.monotonic()
    for i in range(steps):
        ctl.straggler.observe(0, i, now=t0 + i * fast)
        ctl.straggler.observe(1, i, now=t0 + i * slow)


def test_controller_drain_is_off_by_default(server):
    """The policy knob is an explicit ask: with drain_windows=0 a
    permanent straggler verdict NEVER drains — attribution only."""
    ctl = _stub_controller(server, job_id="ctl-drain-off")
    assert ctl.drain_windows == 0
    _feed_straggler_windows(ctl)
    for _ in range(10):
        ctl._maybe_drain(ctl._judge_stragglers())
    assert not ctl.state.members[1].quarantined
    assert ctl.state.pending_failures == []


def test_controller_drain_arms_after_n_consecutive_windows(server):
    from paddle_tpu.observability import events as obs_events
    obs_events._reset_for_tests()
    ctl = _stub_controller(server, job_id="ctl-drain")
    ctl.drain_windows = 3
    drains0 = ctl._drains.collect()
    _feed_straggler_windows(ctl)
    ctl._maybe_drain(ctl._judge_stragglers())
    ctl._maybe_drain(ctl._judge_stragglers())
    # hysteresis: 2 consecutive windows < 3 — no action yet
    assert not ctl.state.members[1].quarantined
    ctl._maybe_drain(ctl._judge_stragglers())
    dead = ctl.state.members[1]
    assert dead.quarantined and dead.proc.killed
    assert ctl.state.pending_failures == [1]
    assert ctl._drains.collect() == drains0 + 1
    # quarantine took the normal failure path: the promotion machinery
    # picks the rank up exactly like a crash
    assert ctl._try_promote(1) is True
    assert ctl.state.members[1].member_id == "spare-0"
    # verdict AND arming progress forgotten on quarantine — the
    # promoted successor starts fresh (absent, not inherited)
    assert 1 not in ctl._straggler_streak
    from paddle_tpu.observability import export as obs_export
    snap = obs_export.snapshot(materialize=False)
    assert 'fleet_straggler{rank="1"}' not in snap
    # the decision ring has the full story in order
    kinds = [e["kind"] for e in obs_events.snapshot()]
    assert kinds.index("drain") < kinds.index("quarantine") < \
        kinds.index("promote")
    drain_ev = next(e for e in obs_events.snapshot()
                    if e["kind"] == "drain")
    assert drain_ev["rank"] == 1 and drain_ev["windows"] == 3
    assert drain_ev["step_time_s"] > drain_ev["median_s"]
    obs_events._reset_for_tests()


def test_controller_drain_streak_resets_on_healthy_window(server):
    ctl = _stub_controller(server, job_id="ctl-drain-reset")
    ctl.drain_windows = 3
    _feed_straggler_windows(ctl)
    ctl._maybe_drain(ctl._judge_stragglers())
    ctl._maybe_drain(ctl._judge_stragglers())
    assert ctl._straggler_streak.get(1) == 2
    # rank 1 recovers to the fleet pace: the arming progress resets
    # to zero (consecutive means consecutive)
    t0 = time.monotonic() + 4.0
    for i in range(8, 30):
        ctl.straggler.observe(0, i, now=t0 + (i - 8) * 0.1)
        ctl.straggler.observe(1, i, now=t0 + (i - 8) * 0.1)
    ctl._maybe_drain(ctl._judge_stragglers())
    assert 1 not in ctl._straggler_streak
    assert not ctl.state.members[1].quarantined


def test_controller_drain_refused_without_live_spare(server):
    """A slow rank still makes progress; a drained one would not —
    with no live spare parked the armed drain is REFUSED (counted
    once per arming), and fires as soon as a spare appears while the
    verdict persists."""
    from paddle_tpu.distributed.launch.controller import _Member
    ctl = _stub_controller(server, job_id="ctl-drain-nospare")
    ctl.drain_windows = 2
    ctl.state.spares = []
    skipped0 = ctl._drains_skipped.collect()
    _feed_straggler_windows(ctl)
    for _ in range(4):
        ctl._maybe_drain(ctl._judge_stragglers())
    assert not ctl.state.members[1].quarantined
    assert ctl.state.pending_failures == []
    # once per arming, not once per 4 Hz tick
    assert ctl._drains_skipped.collect() == skipped0 + 1
    ctl.state.spares = [_Member("spare-9", _StubProc(), "", rank=None)]
    ctl._maybe_drain(ctl._judge_stragglers())
    assert ctl.state.members[1].quarantined


def test_controller_drain_budget_never_double_spends_one_spare(
        server):
    """Review catch: two stragglers arming in the SAME pass must not
    both pass the spare check while only one spare is parked — the
    second drain would kill a rank with no replacement and fail the
    job.  The pool is a budget (live spares minus pending claims),
    decremented as drains commit within the pass."""
    ctl = _stub_controller(server, job_id="ctl-drain-budget")
    ctl.drain_windows = 2
    # both ranks armed simultaneously (the 4-rank two-slow-chips
    # scenario, collapsed to the budget decision)
    ctl._straggler_streak = {0: 2, 1: 2}
    verdicts = {r: {"step_time_s": 0.5, "median_s": 0.1,
                    "straggler": True} for r in (0, 1)}
    ctl._maybe_drain(verdicts)
    drained = [r for r in (0, 1)
               if ctl.state.members[r].quarantined]
    assert len(drained) == 1, "one spare must drain exactly one rank"
    assert ctl.state.pending_failures == drained
    # a pending claim keeps holding the budget on the NEXT pass too
    survivor = ({0, 1} - set(drained)).pop()
    ctl._straggler_streak[survivor] = 5
    ctl._maybe_drain(verdicts)
    assert not ctl.state.members[survivor].quarantined
    # promotion consumes the claim; the (respawned) pool then covers
    # the survivor on a later pass
    assert ctl._try_promote(drained[0]) is True
    ctl.state.pending_failures.remove(drained[0])  # the watch loop's
    # half of a successful promotion
    from paddle_tpu.distributed.launch.controller import _Member
    ctl.state.spares = [_Member("spare-9", _StubProc(), "", rank=None)]
    ctl._maybe_drain(verdicts)
    assert ctl.state.members[survivor].quarantined


def test_controller_drain_decision_is_injectable(server, capsys):
    """member.drain is chaos surface like member.promote: an injected
    failure aborts THAT decision (rank untouched, no counter tick)
    and the persisting verdict retries next window."""
    ctl = _stub_controller(server, job_id="ctl-drain-chaos")
    ctl.drain_windows = 2
    drains0 = ctl._drains.collect()
    _feed_straggler_windows(ctl)
    install(FaultPlan.from_json(
        '[{"site":"member.drain","action":"error","at":1,'
        '"count":1}]'))
    ctl._maybe_drain(ctl._judge_stragglers())
    ctl._maybe_drain(ctl._judge_stragglers())   # armed, but injected
    assert not ctl.state.members[1].quarantined
    assert ctl._drains.collect() == drains0
    assert "will retry" in capsys.readouterr().err
    ctl._maybe_drain(ctl._judge_stragglers())   # retry lands
    clear()
    assert ctl.state.members[1].quarantined
    assert ctl._drains.collect() == drains0 + 1


def test_controller_fleet_healthz_and_events_routes(server):
    """/fleet/healthz: one-glance member health from watch-loop state;
    /fleet/events: the decision ring, source-tagged."""
    from paddle_tpu.observability import events as obs_events
    obs_events._reset_for_tests()
    ctl = _stub_controller(server, job_id="ctl-healthz")
    status, ctype, body = ctl._fleet_healthz_route()
    assert status == 200 and "json" in ctype
    h = json.loads(body)
    assert h["status"] == "ok" and h["spares_available"] == 1
    assert [m["rank"] for m in h["members"]] == [0, 1]
    assert all(m["alive"] and not m["quarantined"]
               for m in h["members"])
    ctl._queue_failure(1, "exit rc=143")
    h = json.loads(ctl._fleet_healthz_route()[2])
    assert h["status"] == "degraded"
    assert h["members"][1]["quarantined"] is True
    assert h["pending_failures"] == [1]
    assert h["quarantined_total"] == 1
    _, _, body = ctl._fleet_events_route()
    evs = json.loads(body)["events"]
    assert [e["kind"] for e in evs].count("quarantine") == 1
    assert all(e["source"] == "controller" and "ts" in e for e in evs)
    obs_events._reset_for_tests()


# ---------------------------------------------------------------------------
# multi-node fleet scrape: KV-published member endpoints (ISSUE 13)
# ---------------------------------------------------------------------------
def test_fleet_scrape_resolves_kv_published_endpoints(server):
    """The controller scrapes members where the KV ``obs/<rank>``
    record says they listen — NOT the loopback BASE+1+rank layout —
    and falls back to the layout for ranks without a record."""
    from paddle_tpu.observability import http as obs_http
    ctl = _stub_controller(server, job_id="ctl-multinode")
    ctl.metrics_base = 59000       # deliberately NOT where rank 0 is
    member_srv = obs_http.serve(0)  # the "remote host" endpoint
    try:
        ctl.client.put(
            ctl._kv_key("obs", "0"),
            json.dumps({"host": "127.0.0.1",
                        "port": member_srv.port, "member": "rank-0"}))
        ctl._refresh_obs_endpoints()
        assert ctl._member_obs_endpoint(0) == ("127.0.0.1",
                                               member_srv.port)
        assert ctl._member_obs_endpoint(1) == ("127.0.0.1", 59002)
        payload = ctl._scrape_member(0, "/metrics.json")
        assert payload is not None and "metrics" in payload
        # a torn/garbage record keeps the last known address
        ctl.client.put(ctl._kv_key("obs", "0"), "{not json")
        ctl._refresh_obs_endpoints()
        assert ctl._member_obs_endpoint(0) == ("127.0.0.1",
                                               member_srv.port)
        # quarantine forgets the record — cache AND the KV record
        # behind it, so the next refresh can't re-adopt the dead
        # member's address; a promoted successor is scraped where IT
        # publishes, never at the dead host
        ctl._queue_failure(0, "exit rc=1")
        assert ctl._member_obs_endpoint(0) == ("127.0.0.1", 59001)
        assert ctl.client.get(ctl._kv_key("obs", "0")) is None
        ctl._refresh_obs_endpoints()
        assert ctl._member_obs_endpoint(0) == ("127.0.0.1", 59001)
    finally:
        member_srv.close()


def test_elastic_ctx_publishes_obs_endpoint(server, monkeypatch):
    """Worker half of the multi-node scrape: register() publishes the
    armed endpoint's host:port under obs/<rank>; a parked spare (no
    rank) publishes nothing until promotion."""
    from paddle_tpu.observability import http as obs_http
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    srv = obs_http.serve(0)
    monkeypatch.setattr(obs_http, "active_server", lambda: srv)
    ctx = ElasticRankContext(server.endpoint, "pub", "rank-0", rank=0)
    try:
        ctx.register()
        rec = json.loads(ctx.client.get(ctx._key("obs", "0")))
        assert rec == {"host": "127.0.0.1", "port": srv.port,
                       "member": "rank-0"}
        spare = ElasticRankContext(server.endpoint, "pub", "spare-0",
                                   role="spare")
        assert spare.publish_obs_endpoint() is False
        assert ctx.client.get(ctx._key("obs", "None")) is None
    finally:
        ctx.exit()
        srv.close()


# ---------------------------------------------------------------------------
# retry stats mirrored onto the observability registry
# ---------------------------------------------------------------------------
def test_retry_stats_mirrored_to_observability_registry():
    from paddle_tpu.observability import metrics as obs_metrics
    reg = obs_metrics.registry()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise ConnectionError("transient")
        return "ok"

    before = reg.counter("resilience_retry_retries_total",
                         labels={"site": "obs-mirror"}).collect()
    assert retry_call(flaky, max_attempts=5, base_delay=0.001,
                      label="obs-mirror") == "ok"
    after = reg.counter("resilience_retry_retries_total",
                        labels={"site": "obs-mirror"}).collect()
    assert after == before + 2
    # and the scrape surface sees it
    from paddle_tpu.observability import export as obs_export
    snap = obs_export.snapshot()
    key = 'resilience_retry_attempts_total{site="obs-mirror"}'
    assert key in snap and snap[key]["value"] >= 3


# ---------------------------------------------------------------------------
# chunked / sampled checkpoint digests
# ---------------------------------------------------------------------------
def test_chunked_digest_manifest_verifies_and_detects_corruption(
        tmp_path, monkeypatch):
    """Files larger than the chunk size get per-chunk digests; the
    manifest still verifies clean bytes and still catches a flipped
    byte anywhere (no sampling → every chunk recorded)."""
    from paddle_tpu.distributed.checkpoint import manager as mgr_mod
    monkeypatch.setenv("PADDLE_TPU_CKPT_DIGEST_CHUNK_MB", "0.0005")
    chunk_bytes, sample = mgr_mod._digest_policy()
    assert chunk_bytes == 524 and sample == 0
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        _train1(net, opt, 1)
        mgr.save(1, net, opt, force=True)
        man = json.load(open(os.path.join(
            d, "1", "RESILIENCE_MANIFEST.json")))
        chunked = [m for m in man["files"].values() if "chunks" in m]
        assert chunked, "no file exceeded the tiny chunk size"
        assert all("sha256" not in m for m in chunked)
        assert mgr.verify_step(1)
        # flip one byte deep inside the largest file
        victim_rel = max(man["files"],
                         key=lambda r: man["files"][r]["size"])
        victim = os.path.join(d, "1", victim_rel)
        with open(victim, "r+b") as f:
            f.seek(os.path.getsize(victim) - 3)
            byte = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([byte[0] ^ 0xFF]))
        assert not mgr.verify_step(1)


def test_sampled_digest_size_check_always_stays(tmp_path, monkeypatch):
    """Sampling caps how many chunks are digested (multi-GB shard
    policy) — but truncation is ALWAYS caught by the size check, and
    corruption in a *sampled* chunk is caught too."""
    from paddle_tpu.distributed.checkpoint import manager as mgr_mod
    monkeypatch.setenv("PADDLE_TPU_CKPT_DIGEST_CHUNK_MB", "0.0001")
    monkeypatch.setenv("PADDLE_TPU_CKPT_DIGEST_SAMPLE_CHUNKS", "3")
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        _train1(net, opt, 1)
        mgr.save(1, net, opt, force=True)
        man = json.load(open(os.path.join(
            d, "1", "RESILIENCE_MANIFEST.json")))
        big = {rel: m for rel, m in man["files"].items()
               if "chunks" in m}
        assert big
        rel, meta = max(big.items(), key=lambda kv: kv[1]["size"])
        n_chunks = -(-meta["size"] // meta["chunk_bytes"])
        if n_chunks > 3:
            assert len(meta["chunks"]) == 3       # sampled, not full
            # first and last chunk are always in the sample
            assert "0" in meta["chunks"]
            assert str(n_chunks - 1) in meta["chunks"]
        assert mgr.verify_step(1)
        victim = os.path.join(d, "1", rel)
        # truncation: caught by the size check regardless of sampling
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.truncate(size - 1)
        assert not mgr.verify_step(1)
        with open(victim, "r+b") as f:          # restore size, corrupt
            f.truncate(size)                     # sampled chunk 0
            f.seek(1)
            f.write(b"\xff")
        assert not mgr.verify_step(1)


def test_legacy_wholefile_sha256_manifest_still_verifies(tmp_path):
    """Manifests written by the pre-chunking format (whole-file
    sha256) must keep verifying — upgrade-in-place reads old step
    dirs."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        _train1(net, opt, 1)
        mgr.save(1, net, opt, force=True)
        # rewrite the manifest in the LEGACY format
        man_path = os.path.join(d, "1", "RESILIENCE_MANIFEST.json")
        man = json.load(open(man_path))
        legacy = {}
        for rel in man["files"]:
            p = os.path.join(d, "1", rel)
            legacy[rel] = {"size": os.path.getsize(p),
                           "sha256": CheckpointManager._digest(p)}
        json.dump({"step": 1, "files": legacy}, open(man_path, "w"))
        assert mgr.verify_step(1)
        _corrupt_newest(d, 1)
        assert not mgr.verify_step(1)


def test_rollback_to_quarantines_newer_steps(tmp_path):
    """The reform contract: survivors roll back to the agreed resume
    step; newer step dirs leave the namespace (orbax would refuse the
    re-save) but the bytes survive in _quarantined/."""
    d = str(tmp_path / "c")
    paddle.seed(0)
    net = _Net()
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    with CheckpointManager(d, async_save=False) as mgr:
        for step in (1, 2, 3):
            _train1(net, opt, step)
            mgr.save(step, net, opt, force=True)
        with pytest.warns(UserWarning, match="quarantin"):
            mgr.rollback_to(2)
        assert mgr.all_steps() == [1, 2]
        assert mgr.restore(net, opt, step=2) == 2
        # the resumed run re-saves step 3 without wedging
        _train1(net, opt, 3)
        assert mgr.save(3, net, opt, force=True)
        assert mgr.verify_step(3)
    assert os.path.isdir(os.path.join(d, "_quarantined", "3"))


# ---------------------------------------------------------------------------
# chaos end-to-end (acceptance): dp=2 + 1 hot spare through the REAL
# launch controller — one rank killed (or wedged) mid-run, the spare
# is promoted into its rank id, the SURVIVOR'S PROCESS IS NOT
# RESTARTED, and the resumed run's final losses are bit-identical to
# an uninterrupted run.
# ---------------------------------------------------------------------------
_ELASTIC_WORKER = textwrap.dedent("""
    import os
    import sys
    import time
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.distributed.resilience import faults
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    from paddle_tpu.distributed.runner import DistributedRunner

    TOTAL = int(os.environ.get("E2E_TOTAL_STEPS", "5"))
    # retention horizon: reform proposals are range-aware — the
    # barrier validates min(newest) against every member's oldest
    # retained step and fails loudly (ReformWindowError) when the
    # windows don't intersect, instead of letting a member fail its
    # rollback mid-reform.  E2es whose proposal spread can exceed
    # max_to_keep (straggler drain) size retention to the run so the
    # window stays non-empty.
    KEEP = int(os.environ.get("E2E_CKPT_KEEP", "5"))

    def make_runner(net, opt):
        # E2E_DP_SHARDED (ISSUE 11): each rank runs a LOCAL dp=2 CPU
        # mesh with the compressed (bits=16, the exact parity anchor)
        # + dp-sharded weight update engine, so the reform contract is
        # exercised against dp-SHARDED opt_state — the promoted spare
        # and the survivors re-adopt only their 1/dp shard at restore
        if os.environ.get("E2E_DP_SHARDED"):
            mesh = collective.build_mesh({"dp": 2})
            return DistributedRunner(net, opt, nn.MSELoss(), mesh=mesh,
                                     dp_compress_bits=16,
                                     dp_shard_update=True)
        return DistributedRunner(net, opt, nn.MSELoss(),
                                 mesh=collective.build_mesh({}))

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return self.fc2(F.relu(self.fc1(x)))

    def train_rank(rank, net, runner, mgr, start):
        final = None
        for step in range(start + 1, TOTAL + 1):
            rng = np.random.RandomState(1000 * (rank + 1) + step)
            x = rng.rand(8, 4).astype(np.float32)
            y = rng.rand(8, 2).astype(np.float32)
            final = float(runner.train_step([x], [y]))
            mgr.save(step, net, opt, force=True)
        return final

    if os.environ.get("E2E_REFERENCE_MODE"):
        # the uninterrupted reference: each rank's trajectory is
        # independent and fully deterministic, so ONE process running
        # them sequentially (fresh seed/net/runner per rank — global
        # RNG fully reset by paddle.seed) computes bit-identical
        # losses to the controller-spawned workers, at a quarter of
        # the process-spawn cost
        from paddle_tpu import optimizer as _optim
        for rank in range(int(os.environ.get("E2E_WORLD", "2"))):
            paddle.seed(7 + rank)
            net = Net()
            opt = _optim.Adam(learning_rate=1e-2,
                              parameters=net.parameters())
            mgr = CheckpointManager(
                os.path.join(os.environ["CKPT_ROOT"], f"rank{rank}"),
                async_save=False, max_to_keep=KEEP)
            runner = make_runner(net, opt)
            runner.set_global_step(0)
            final = train_rank(rank, net, runner, mgr, 0)
            mgr.close()
            with open(os.path.join(os.environ["LOSS_DIR"],
                                   f"rank{rank}.loss"), "w") as f:
                f.write(f"{final:.9e}")
            print(f"TRAIN-COMPLETE rank={rank} pid={os.getpid()}",
                  flush=True)
        sys.exit(0)

    ctx = ElasticRankContext.from_env()
    assert ctx is not None, "spawned without rank-elastic env"
    ctx.register()
    print(f"WORKER-START role={ctx.role} member={ctx.member_id} "
          f"pid={os.getpid()}", flush=True)

    promoted_epoch = None
    if ctx.role == "spare":
        ticket = ctx.wait_for_promotion()
        if ticket is None:
            print("SPARE-IDLE-EXIT", flush=True)
            ctx.exit()
            sys.exit(0)
        promoted_epoch = ticket.epoch
        print(f"PROMOTED-TO-RANK {ticket.rank} epoch={ticket.epoch} "
              f"pid={os.getpid()}", flush=True)
    elif os.environ.get("FAULT_RANK") and \
            int(os.environ["FAULT_RANK"]) == ctx.rank:
        # per-rank chaos: only the victim installs the kill/wedge
        # plan (a shared PADDLE_FAULT_PLAN would fire identically in
        # every rank and take the whole pod down)
        faults.install(faults.FaultPlan.from_json(
            os.environ["RANK_FAULT_PLAN"]))

    rank = ctx.rank
    paddle.seed(7 + rank)
    net = Net()
    opt = optimizer.Adam(learning_rate=1e-2,
                         parameters=net.parameters())
    mgr = CheckpointManager(
        os.path.join(os.environ["CKPT_ROOT"], f"rank{rank}"),
        async_save=False, max_to_keep=KEEP)
    runner = make_runner(net, opt)

    def wait_epoch(min_epoch=0):
        while True:
            rec = ctx.read_epoch()
            if rec is not None and int(rec["epoch"]) >= min_epoch:
                return rec
            time.sleep(0.05)

    def do_reform(rec):
        members = sorted(int(r) for r in rec["members"])
        propose = mgr.latest_verified_step() or 0
        oldest = mgr.oldest_verified_step() or 0
        resume = ctx.reform_barrier(int(rec["epoch"]), members,
                                    propose, oldest_step=oldest)
        mgr.rollback_to(resume)
        if resume > 0:
            mgr.restore(net, opt, step=resume)
        runner.invalidate_cache()   # adopt the external restore
        runner.set_global_step(resume)
        print(f"REFORMED epoch={rec['epoch']} resume={resume} "
              f"pid={os.getpid()}", flush=True)
        return int(rec["epoch"]), resume

    if promoted_epoch is not None:
        epoch, start = do_reform(wait_epoch(promoted_epoch))
    else:
        rec = wait_epoch()
        epoch = int(rec["epoch"])
        start = mgr.restore(net, opt)
        runner.set_global_step(start)
    ctx.publish_beacon(step=start, ckpt_step=start)

    final = None
    step = start + 1
    UNCOUPLED = bool(os.environ.get("E2E_UNCOUPLED"))
    STEP_SLEEP = float(os.environ.get("E2E_STEP_SLEEP", "0") or 0)
    while step <= TOTAL:
        if UNCOUPLED:
            # free-running ranks (the straggler auto-drain e2e):
            # attribution needs per-rank pace — a lockstep barrier
            # would couple the healthy rank's step-time to the slow
            # rank's.  Membership changes are noticed at the step
            # boundary instead of inside the barrier wait.
            rec = ctx.read_epoch()
            ev = (rec if rec is not None
                  and int(rec.get("epoch", -1)) != epoch else None)
        else:
            ev = ctx.step_barrier(step, epoch)
        if ev is not None:               # membership changed mid-wait
            epoch, resume = do_reform(ev)
            step = resume + 1
            continue
        if STEP_SLEEP:
            # a baseline per-step cost, so the injected-latency rank
            # is measurably SLOWER (not just "slow vs instant")
            time.sleep(STEP_SLEEP)
        rng = np.random.RandomState(1000 * (rank + 1) + step)
        x = rng.rand(8, 4).astype(np.float32)
        y = rng.rand(8, 2).astype(np.float32)
        # a kill/wedge fault fires inside train_step, after the step
        # commits but before its checkpoint lands — the production
        # preemption window
        final = float(runner.train_step([x], [y]))
        mgr.save(step, net, opt, force=True)
        ctx.publish_beacon(step=step, ckpt_step=step)
        step += 1
    mgr.close()
    with open(os.path.join(os.environ["LOSS_DIR"],
                           f"rank{rank}.loss"), "w") as f:
        f.write(f"{final:.9e}")
    print(f"TRAIN-COMPLETE rank={rank} pid={os.getpid()}", flush=True)
    ctx.exit()
""")


def _elastic_pod_cmd_env(tmp_path, name, extra_env=None, spares=1,
                         beacon_timeout=10.0, extra_args=None):
    """Shared launch-command/env assembly for the controller e2es."""
    work = tmp_path / name
    work.mkdir()
    (work / "loss").mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env["CKPT_ROOT"] = str(work / "ckpt")
    env["LOSS_DIR"] = str(work / "loss")
    env.pop("PADDLE_FAULT_PLAN", None)
    env.pop("FAULT_RANK", None)
    env.update(extra_env or {})
    script = tmp_path / "elastic_worker.py"
    if not script.exists():
        script.write_text(_ELASTIC_WORKER)
    # REFERENCE_MODE never leaks into a pod run
    env.pop("E2E_REFERENCE_MODE", None)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--spares", str(spares),
           "--beacon_timeout", str(beacon_timeout),
           "--job_id", name, "--log_dir", str(work / "log"),
           *(extra_args or []), str(script)]
    return cmd, env, work


def _read_pod_logs(work):
    logs = {}
    for fname in ("workerlog.0", "workerlog.1", "sparelog.0"):
        p = work / "log" / fname
        logs[fname] = p.read_text() if p.exists() else ""
    return logs


def _run_elastic_pod(tmp_path, name, extra_env=None, spares=1,
                     beacon_timeout=10.0, timeout=420):
    """One controller run: dp=2 ranks + spares through
    ``launch --spares`` (embedded KV registry)."""
    cmd, env, work = _elastic_pod_cmd_env(
        tmp_path, name, extra_env=extra_env, spares=spares,
        beacon_timeout=beacon_timeout)
    proc = subprocess.run(cmd, env=env, cwd=str(work),
                          capture_output=True, text=True,
                          timeout=timeout)
    return proc, _read_pod_logs(work), work


def _losses(work, world=2):
    out = {}
    for r in range(world):
        p = work / "loss" / f"rank{r}.loss"
        if p.exists():
            out[r] = float(p.read_text())
    return out


@pytest.fixture(scope="module")
def elastic_reference(tmp_path_factory):
    """The uninterrupted run both chaos e2es compare against.  Each
    rank's trajectory is independent and deterministic, so ONE
    process computes both final losses bit-identically to the
    controller-spawned workers (REFERENCE_MODE in the worker) — a
    quarter of the process-spawn cost of a full pod."""
    tmp = tmp_path_factory.mktemp("elastic_ref")
    work = tmp / "ref"
    work.mkdir()
    (work / "loss").mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env["CKPT_ROOT"] = str(work / "ckpt")
    env["LOSS_DIR"] = str(work / "loss")
    env["E2E_REFERENCE_MODE"] = "1"
    env.pop("PADDLE_FAULT_PLAN", None)
    script = tmp / "elastic_worker.py"
    script.write_text(_ELASTIC_WORKER)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          cwd=str(work), capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref = _losses(work)
    assert sorted(ref) == [0, 1], ref
    return ref


def _assert_promotion_recovery(proc, logs, work, ref):
    """Shared post-conditions of both chaos e2es."""
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstderr:\n{proc.stderr[-3000:]}\n"
        f"log0:\n{logs['workerlog.0'][-2000:]}\n"
        f"log1:\n{logs['workerlog.1'][-2000:]}\n"
        f"spare:\n{logs['sparelog.0'][-2000:]}")
    # the spare was promoted into the dead rank id and finished its work
    assert "PROMOTED-TO-RANK 1" in logs["sparelog.0"]
    assert "TRAIN-COMPLETE rank=1" in logs["sparelog.0"]
    assert "promoted spare spare-0 into rank 1" in proc.stdout
    # THE acceptance pin: the surviving rank's process was NOT
    # restarted — exactly one incarnation, and the pid that started
    # is the pid that finished
    starts = [l for l in logs["workerlog.0"].splitlines()
              if l.startswith("WORKER-START")]
    assert len(starts) == 1, starts
    pid = starts[0].split("pid=")[1].strip()
    assert f"TRAIN-COMPLETE rank=0 pid={pid}" in logs["workerlog.0"]
    # ...but it DID re-form membership in place (state rollback, same
    # process)
    assert "REFORMED epoch=1" in logs["workerlog.0"]
    # bit-identical final losses vs the uninterrupted run, both ranks
    chaos = _losses(work)
    assert sorted(chaos) == [0, 1], chaos
    for r in (0, 1):
        np.testing.assert_allclose(chaos[r], ref[r], rtol=0, atol=0)


@pytest.mark.dist
def test_chaos_e2e_rank_killed_spare_promoted_survivor_not_restarted(
        tmp_path, elastic_reference):
    """Rank 1 is killed by a deterministic FaultPlan crash inside
    train step 3 (the preemption window: step committed, checkpoint
    not yet saved).  The controller must quarantine it and promote
    the hot spare into rank 1; rank 0's process must survive the
    whole event; the re-formed run must finish with final losses
    bit-identical to the uninterrupted reference.  The
    ``member.promote`` site is chaos-injected to fail once on top, so
    the promotion retry path runs inside the acceptance scenario
    too."""
    proc, logs, work = _run_elastic_pod(
        tmp_path, "kill",
        extra_env={
            "FAULT_RANK": "1",
            "RANK_FAULT_PLAN": (
                '[{"site":"train.step","action":"crash",'
                '"match":{"step":3},"exit_code":143}]'),
            # controller-side chaos: first promotion attempt fails
            "PADDLE_FAULT_PLAN": (
                '[{"site":"member.promote","action":"error",'
                '"at":1,"count":1}]'),
        })
    assert "injected crash at train.step" in logs["workerlog.1"]
    assert "failed: exit rc=143" in proc.stderr
    # the injected member.promote failure was retried
    assert "will retry" in proc.stderr
    _assert_promotion_recovery(proc, logs, work, elastic_reference)


@pytest.mark.dist
@pytest.mark.slow
def test_chaos_e2e_wedged_rank_detected_by_beacon_cross_check(
        tmp_path, elastic_reference):
    """The wedged-chip scenario: rank 1's train step 3 stalls forever
    (injected latency) — its process stays alive and its KV heartbeat
    keeps beating, so ONLY the data-plane beacon cross-check can see
    the wedge.  The controller must SIGKILL the zombie, promote the
    spare, and the run must recover exactly like the kill case."""
    # 9s beacon budget: the only frozen-beacon window of a HEALTHY
    # rank is its step-1 jit compile (~1-2s; barrier beats cover all
    # waiting) — sized generously so a loaded container can't trip a
    # false wedge verdict on the survivor
    proc, logs, work = _run_elastic_pod(
        tmp_path, "wedge", beacon_timeout=9.0,
        extra_env={
            "FAULT_RANK": "1",
            "RANK_FAULT_PLAN": (
                '[{"site":"train.step","action":"latency",'
                '"latency_s":600,"match":{"step":3}}]'),
        })
    # the replacement decision came from the cross-check, not from a
    # process exit or heartbeat loss
    assert "data-plane cross-check" in proc.stderr
    assert "beacon stalled" in proc.stderr
    assert "failed: beacon" in proc.stderr
    _assert_promotion_recovery(proc, logs, work, elastic_reference)


_SHARDED_ENV = {
    "E2E_DP_SHARDED": "1",
    # each rank process needs its own 2 virtual devices for the local
    # dp=2 mesh (the pod default strips the device-count flag)
    "XLA_FLAGS": ("--xla_force_host_platform_device_count=2"
                  " --xla_backend_optimization_level=0"),
}


@pytest.mark.dist
@pytest.mark.slow
def test_chaos_e2e_kill_with_dp_sharded_opt_state(tmp_path):
    """ISSUE 11 sharded elastic restore: the PR-9 kill e2e with every
    rank running the compressed (bits=16) + dp-SHARDED weight-update
    engine on a local dp=2 mesh.  Rank 1 dies inside step 3, the
    spare is promoted, reform rolls back and restores from the
    (full-layout) checkpoint — `invalidate_cache` re-adopts the
    optimizer moments dp-SHARDED, so the promoted spare and the
    survivor each re-place only their 1/dp shard — and the run
    finishes with final losses bit-identical to an uninterrupted
    sharded run."""
    # reference: one process, both ranks sequentially (the PR-9
    # REFERENCE_MODE argument), under the SAME sharded config
    ref_work = tmp_path / "ref"
    ref_work.mkdir()
    (ref_work / "loss").mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["CKPT_ROOT"] = str(ref_work / "ckpt")
    env["LOSS_DIR"] = str(ref_work / "loss")
    env["E2E_REFERENCE_MODE"] = "1"
    env.update(_SHARDED_ENV)
    env.pop("PADDLE_FAULT_PLAN", None)
    script = tmp_path / "elastic_worker.py"
    script.write_text(_ELASTIC_WORKER)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          cwd=str(ref_work), capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref = _losses(ref_work)
    assert sorted(ref) == [0, 1], ref

    proc, logs, work = _run_elastic_pod(
        tmp_path, "kill_sharded",
        extra_env={
            **_SHARDED_ENV,
            "FAULT_RANK": "1",
            "RANK_FAULT_PLAN": (
                '[{"site":"train.step","action":"crash",'
                '"match":{"step":3},"exit_code":143}]'),
        })
    assert "injected crash at train.step" in logs["workerlog.1"]
    _assert_promotion_recovery(proc, logs, work, ref)


# ---------------------------------------------------------------------------
# ISSUE 13 acceptance: straggler AUTO-DRAIN through the real launch
# controller — injected per-step latency, drain verdict, spare
# promotion, reform, bit-identical end state; every decision visible
# on /fleet/events and the controller registry while the job runs
# ---------------------------------------------------------------------------
_DRAIN_ENV = {
    # free-running ranks (attribution needs per-rank pace) with a
    # 0.3 s baseline step so "slow" is a ratio, not a race
    "E2E_UNCOUPLED": "1",
    "E2E_STEP_SLEEP": "0.3",
    "E2E_TOTAL_STEPS": "28",
    # retention must reach back to the reform's min-over-proposals:
    # the drained rank's newest checkpoint is MANY steps behind the
    # fast rank by design here (DESIGN-RESILIENCE.md §Known limits)
    "E2E_CKPT_KEEP": "40",
}


def _get_json_quiet(url, timeout=2.0):
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))
    except Exception:
        return None


@pytest.mark.dist
@pytest.mark.slow
def test_chaos_e2e_straggler_auto_drained_and_recovers(tmp_path):
    """THE action-loop acceptance (ISSUE 13): rank 1 is not dead and
    not wedged — it makes progress 1.2 s/step slower than the fleet
    (injected latency on every train.step).  Only the straggler
    policy can see that.  With --drain_stragglers armed the
    controller must: attribute, hold the verdict N consecutive
    windows, drain (kill + quarantine) the slow rank, promote the
    spare, and the re-formed run must finish with both final losses
    bit-identical to an uninterrupted run — with rank 0's process
    never restarted, and the drain decision visible on /fleet/events
    + fleet_drains_total while the job runs."""
    import socket as _socket
    # uninterrupted reference (REFERENCE_MODE, no sleeps — sleeps are
    # pacing, not math)
    ref_work = tmp_path / "ref"
    ref_work.mkdir()
    (ref_work / "loss").mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env["CKPT_ROOT"] = str(ref_work / "ckpt")
    env["LOSS_DIR"] = str(ref_work / "loss")
    env["E2E_REFERENCE_MODE"] = "1"
    env["E2E_TOTAL_STEPS"] = _DRAIN_ENV["E2E_TOTAL_STEPS"]
    env.pop("PADDLE_FAULT_PLAN", None)
    script = tmp_path / "elastic_worker.py"
    script.write_text(_ELASTIC_WORKER)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          cwd=str(ref_work), capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref = _losses(ref_work)
    assert sorted(ref) == [0, 1], ref

    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
    cmd, env, work = _elastic_pod_cmd_env(
        tmp_path, "drain",
        extra_env={
            **_DRAIN_ENV,
            "FAULT_RANK": "1",
            # latency, not crash and not a freeze: the rank keeps
            # committing steps (beacon moves — the wedge cross-check
            # must NOT fire), just 1.2 s late, every step
            "RANK_FAULT_PLAN": (
                '[{"site":"train.step","action":"latency",'
                '"latency_s":1.2,"at":1,"count":-1}]'),
        },
        beacon_timeout=30.0,   # far above the 1.5 s/step slow pace:
        # the ONLY path allowed to replace this rank is the drain
        extra_args=["--metrics_port", str(base),
                    "--straggler_factor", "2.0",
                    "--drain_stragglers", "6"])
    pod = subprocess.Popen(cmd, env=env, cwd=str(work),
                           stdout=subprocess.PIPE,
                           stderr=subprocess.PIPE, text=True)
    drain_ev = None
    try:
        # the acceptance is OBSERVABILITY-first: watch the drain land
        # on /fleet/events from outside while the job runs
        deadline = time.time() + 150
        while time.time() < deadline and pod.poll() is None:
            payload = _get_json_quiet(
                f"http://127.0.0.1:{base}/fleet/events")
            if payload:
                for e in payload.get("events", []):
                    if e.get("kind") == "drain":
                        drain_ev = e
                        break
            if drain_ev:
                break
            time.sleep(0.5)
        assert drain_ev is not None, "no drain event within budget"
        assert drain_ev["rank"] == 1 and drain_ev["windows"] >= 6
        assert drain_ev["source"] == "controller"
        # the registry saw the same decision, and /fleet/healthz
        # shows the quarantine
        metrics = None
        for _ in range(20):
            try:
                import urllib.request
                metrics = urllib.request.urlopen(
                    f"http://127.0.0.1:{base}/metrics",
                    timeout=2).read().decode()
                break
            except Exception:
                time.sleep(0.5)
        assert metrics and "fleet_drains_total 1" in metrics
        h = _get_json_quiet(f"http://127.0.0.1:{base}/fleet/healthz")
        assert h is not None and h["quarantined_total"] >= 1
        assert h["drain_windows"] == 6
        out, err = pod.communicate(timeout=240)
    except BaseException:
        pod.kill()
        pod.communicate()
        raise
    logs = _read_pod_logs(work)
    assert pod.returncode == 0, (
        f"rc={pod.returncode}\nstderr:\n{err[-3000:]}\n"
        f"log0:\n{logs['workerlog.0'][-2000:]}\n"
        f"log1:\n{logs['workerlog.1'][-2000:]}\n"
        f"spare:\n{logs['sparelog.0'][-2000:]}")
    # the decision came from the drain policy — not an exit, not a
    # heartbeat loss, not the beacon cross-check
    assert "auto-drain: rank 1" in err
    assert "failed: straggler" in err
    assert "data-plane cross-check" not in err
    # spare promoted into rank 1 and finished the run
    assert "PROMOTED-TO-RANK 1" in logs["sparelog.0"]
    assert "TRAIN-COMPLETE rank=1" in logs["sparelog.0"]
    # rank 0's process survived the whole event (one incarnation)
    starts = [l for l in logs["workerlog.0"].splitlines()
              if l.startswith("WORKER-START")]
    assert len(starts) == 1, starts
    pid = starts[0].split("pid=")[1].strip()
    assert f"TRAIN-COMPLETE rank=0 pid={pid}" in logs["workerlog.0"]
    assert "REFORMED epoch=1" in logs["workerlog.0"]
    # bit-identical final losses vs the uninterrupted reference
    chaos = _losses(work)
    assert sorted(chaos) == [0, 1], chaos
    for r in (0, 1):
        np.testing.assert_allclose(chaos[r], ref[r], rtol=0, atol=0)


# ---------------------------------------------------------------------------
# ISSUE 18 acceptance: multi-host elastic fleet — two host agents
# (virtual host ids, one shared KV registry), SIGKILL of an ENTIRE
# node, node-death verdict from the frozen lease, batch promotion of
# both lost ranks under ONE epoch, bit-identical end state
# ---------------------------------------------------------------------------
_MULTIHOST_ENV = {
    # pace the steps so the external SIGKILL lands mid-run (the
    # single-node e2es crash deterministically from INSIDE the
    # victim; a whole-node kill is necessarily an outside event)
    "E2E_TOTAL_STEPS": "12",
    "E2E_STEP_SLEEP": "0.4",
    # retention reaches back across the ~3 s death-verdict window
    "E2E_CKPT_KEEP": "40",
}


@pytest.mark.dist
@pytest.mark.slow
def test_chaos_e2e_node_death_batch_promotion(tmp_path):
    """THE multi-host acceptance (ISSUE 18): a 2-node pod — two
    ``launch --agent`` daemons with virtual host ids against one KV
    server — runs 4 ranks + 2 spares per node.  Host h1 (agent AND
    both its worker pids) is SIGKILLed mid-run.  The controller must
    judge NODE DEATH from the frozen lease (no process exit is
    observable across hosts), quarantine BOTH of h1's ranks in one
    pass, batch-promote the two surviving spares under a SINGLE
    epoch bump, and the re-formed 4-rank run must finish with final
    losses bit-identical to an uninterrupted reference — with the
    node_death decision visible on /fleet/events while the job
    runs."""
    import signal as _signal
    import socket as _socket
    from paddle_tpu.distributed.resilience.elastic_rank import kv_key

    # uninterrupted 4-rank reference (one process, sequential ranks)
    ref_work = tmp_path / "ref"
    ref_work.mkdir()
    (ref_work / "loss").mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env["CKPT_ROOT"] = str(ref_work / "ckpt")
    env["LOSS_DIR"] = str(ref_work / "loss")
    env["E2E_REFERENCE_MODE"] = "1"
    env["E2E_WORLD"] = "4"
    env["E2E_TOTAL_STEPS"] = _MULTIHOST_ENV["E2E_TOTAL_STEPS"]
    env.pop("PADDLE_FAULT_PLAN", None)
    script = tmp_path / "elastic_worker.py"
    script.write_text(_ELASTIC_WORKER)
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          cwd=str(ref_work), capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    ref = _losses(ref_work, world=4)
    assert sorted(ref) == [0, 1, 2, 3], ref

    # the shared registry is test-owned (NOT controller-embedded):
    # agents must outlive any one controller, that is the point
    kv = KVServer().start()
    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
    name = "nodedeath"
    cmd, env, work = _elastic_pod_cmd_env(
        tmp_path, name, extra_env=_MULTIHOST_ENV, spares=2,
        beacon_timeout=30.0,   # the ONLY path allowed to replace
        # h1's ranks is the node-lease judgment (worker heartbeats
        # outlive it: server ttl 6 s + grace > lease timeout 3 s)
        extra_args=["--nnodes", "2",
                    "--elastic_server", kv.endpoint,
                    "--metrics_port", str(base)])
    agent_cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--agent", "--elastic_server", kv.endpoint,
                 "--job_id", name, "--log_dir", str(work / "log")]
    agents, agent_logs, pod, death_ev = {}, {}, None, None
    client = KVClient(kv.endpoint)
    try:
        for host in ("h0", "h1"):
            agent_logs[host] = open(work / f"agent_{host}.log", "w")
            agents[host] = subprocess.Popen(
                agent_cmd + ["--host_id", host], env=env,
                cwd=str(work), stdout=agent_logs[host],
                stderr=subprocess.STDOUT, text=True)
        pod = subprocess.Popen(cmd, env=env, cwd=str(work),
                               stdout=subprocess.PIPE,
                               stderr=subprocess.PIPE, text=True)

        def wait_for(fn, what, budget=90.0):
            deadline = time.time() + budget
            while time.time() < deadline:
                assert pod.poll() is None, \
                    f"controller died while waiting for {what}"
                got = fn()
                if got is not None:
                    return got
                time.sleep(0.2)
            raise AssertionError(f"no {what} within {budget}s")

        run_id = wait_for(
            lambda: (json.loads(client.get(kv_key(name, "run")))
                     ["run_id"]
                     if client.get(kv_key(name, "run")) else None),
            "run record")

        def h1_rank_beacon():
            raw = client.get(kv_key(name, "beacon", "2",
                                    run_id=run_id))
            if raw and json.loads(raw).get("step", -1) >= 2:
                return raw
            return None

        wait_for(h1_rank_beacon, "rank-2 progress past step 2", 120.0)
        lease = json.loads(client.get(kv_key(name, "node", "h1",
                                             run_id=run_id)))
        victims = sorted(p["pid"] for p in lease["procs"].values()
                         if p["pid"] is not None and p["rc"] is None)
        assert len(victims) == 4, lease    # 2 ranks + 2 spares on h1
        # kill the WHOLE node: agent first (a surviving agent would
        # report its workers' exit codes and turn this into four
        # ordinary exit-rc failures — the node verdict must come
        # from the frozen lease alone), then every process it held
        agents["h1"].kill()
        agents["h1"].wait(timeout=30)
        for pid in victims:
            try:
                os.kill(pid, _signal.SIGKILL)
            except ProcessLookupError:
                pass
        # observability-first acceptance: the node_death decision is
        # readable on /fleet/events from outside while the job runs
        def node_death_event():
            payload = _get_json_quiet(
                f"http://127.0.0.1:{base}/fleet/events")
            for e in (payload or {}).get("events", []):
                if e.get("kind") == "node_death":
                    return e
            return None

        death_ev = wait_for(node_death_event, "node_death event")
        assert death_ev["host"] == "h1"
        assert death_ev["ranks"] == [2, 3]
        h = _get_json_quiet(f"http://127.0.0.1:{base}/fleet/healthz")
        if h is not None and "nodes" in h:
            nodes = {n["host"]: n for n in h["nodes"]}
            assert nodes["h1"]["alive"] is False
        out, err = pod.communicate(timeout=240)
        # the surviving agent winds down with the job
        agents["h0"].wait(timeout=60)
    except BaseException:
        if pod is not None:
            pod.kill()
            pod.communicate()
        raise
    finally:
        for host, a in agents.items():
            if a.poll() is None:
                a.kill()
                a.wait()
        for f in agent_logs.values():
            f.close()
        kv.stop()
    logs = {}
    for host in ("h0", "h1"):
        for fname in ("workerlog.0", "workerlog.1", "workerlog.2",
                      "workerlog.3", "sparelog.0", "sparelog.1",
                      "sparelog.2", "sparelog.3"):
            p = work / "log" / host / fname
            if p.exists():
                logs[f"{host}/{fname}"] = p.read_text()
    assert pod.returncode == 0, (
        f"rc={pod.returncode}\nstderr:\n{err[-4000:]}\n"
        f"logs: {sorted(logs)}\n"
        f"log h0/0:\n{logs.get('h0/workerlog.0', '')[-2000:]}")
    # the verdict was NODE death — one pass, both ranks — not two
    # independent member failures
    assert "NODE DEATH: host h1" in err
    assert "quarantining its ranks [2, 3]" in err
    # batch promotion landed under ONE epoch: both spares on the
    # surviving host promoted into the lost ranks at epoch 1
    assert "promoted spare spare-0 into rank 2 (epoch 1)" in out
    assert "promoted spare spare-2 into rank 3 (epoch 1)" in out
    assert "(epoch 2)" not in out
    assert "PROMOTED-TO-RANK 2 epoch=1" in logs["h0/sparelog.0"]
    assert "PROMOTED-TO-RANK 3 epoch=1" in logs["h0/sparelog.2"]
    assert "TRAIN-COMPLETE rank=2" in logs["h0/sparelog.0"]
    assert "TRAIN-COMPLETE rank=3" in logs["h0/sparelog.2"]
    # the survivors on h0 were NOT restarted: one incarnation each,
    # re-formed in place at epoch 1
    for r in (0, 1):
        log = logs[f"h0/workerlog.{r}"]
        starts = [l for l in log.splitlines()
                  if l.startswith("WORKER-START")]
        assert len(starts) == 1, starts
        pid = starts[0].split("pid=")[1].strip()
        assert f"TRAIN-COMPLETE rank={r} pid={pid}" in log
        assert "REFORMED epoch=1" in log
    # bit-identical final losses vs the uninterrupted 4-rank run
    chaos = _losses(work, world=4)
    assert sorted(chaos) == [0, 1, 2, 3], chaos
    for r in range(4):
        np.testing.assert_allclose(chaos[r], ref[r], rtol=0, atol=0)


# ---------------------------------------------------------------------------
# beacon wiring: fleet arming from env + runner step feed
# ---------------------------------------------------------------------------
def test_fleet_enable_resilience_arms_beacon_from_env(
        server, monkeypatch):
    from paddle_tpu.distributed.fleet.fleet import fleet_instance
    from paddle_tpu.distributed.resilience import (current_context,
                                                   install_context)
    monkeypatch.setenv("PADDLE_ELASTIC_SERVER", server.endpoint)
    monkeypatch.setenv("PADDLE_MEMBER_ID", "rank-0")
    monkeypatch.setenv("PADDLE_JOB_ID", "arm")
    monkeypatch.setenv("PADDLE_RANK_ROLE", "rank")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    try:
        fleet_instance.enable_resilience()    # no watchdog, just arm
        ctx = current_context()
        assert ctx is not None and ctx.rank == 0
        assert ctx.beacon_min_interval > 0    # hot-loop rate limit
        # heartbeat registered under the job prefix
        deadline = time.time() + 5
        while time.time() < deadline:
            if "arm/rank-0" in ctx.client.members("arm/"):
                break
            time.sleep(0.1)
        assert "arm/rank-0" in ctx.client.members("arm/")
        # idempotent: a second call never clobbers the armed context
        fleet_instance.enable_resilience()
        assert current_context() is ctx
    finally:
        c = current_context()
        if c is not None:
            c.exit()
        install_context(None)


def test_runner_feeds_beacon_steps(server):
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.resilience import install_context
    from paddle_tpu.distributed.resilience.elastic_rank import (
        ElasticRankContext)
    from paddle_tpu.distributed.runner import DistributedRunner
    ctx = ElasticRankContext(server.endpoint, "rf", "rank-0", rank=0)
    install_context(ctx)
    try:
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = optimizer.Adam(1e-2, parameters=net.parameters())
        r = DistributedRunner(net, opt, nn.MSELoss(),
                              mesh=collective.build_mesh({}))
        x = np.random.RandomState(0).rand(4, 4).astype(np.float32)
        y = np.random.RandomState(1).rand(4, 2).astype(np.float32)
        r.train_step([x], [y])
        r.train_step([x], [y])
        beacon = json.loads(ctx.client.get("/k/rf/beacon/0"))
        assert beacon["step"] == 2 and beacon["beat"] >= 2
    finally:
        install_context(None)
        ctx.exit()
