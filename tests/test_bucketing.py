"""Dynamic-shape bucketing: bucket assignment, padding, sampler, and
the one-program-per-bucket property under jit."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.io.bucketing import (BucketBatchSampler, PadToBuckets,
                                     pad_batch, shape_bucket)


def test_shape_bucket():
    assert shape_bucket(5, [8, 16, 32]) == 8
    assert shape_bucket(8, [8, 16, 32]) == 8
    assert shape_bucket(9, [8, 16, 32]) == 16
    assert shape_bucket(100, [8, 16, 32]) == 32  # clamps to largest


def test_pad_batch_and_mask():
    arrays = [np.ones((3, 2), np.float32), np.ones((7, 2), np.float32)]
    out, mask = pad_batch(arrays, [4, 8], axis=0)
    assert out.shape == (2, 8, 2)
    assert mask.shape == (2, 8)
    assert mask[0].sum() == 3 and mask[1].sum() == 7
    assert out[0, 3:].sum() == 0


class Ragged(Dataset):
    def __init__(self, lengths):
        self.lengths = lengths

    def __len__(self):
        return len(self.lengths)

    def __getitem__(self, i):
        n = self.lengths[i]
        return np.full((n, 4), i, np.float32), np.int64(i)


def test_bucket_batch_sampler_groups_by_bucket():
    lengths = [3, 5, 9, 15, 4, 12, 7, 8]
    ds = Ragged(lengths)
    bs = BucketBatchSampler(ds, batch_size=2, buckets=[8, 16],
                            size_fn=lambda i: lengths[i])
    batches = list(bs)
    assert len(bs) == len(batches)
    for batch in batches:
        buckets = {shape_bucket(lengths[i], [8, 16]) for i in batch}
        assert len(buckets) == 1, "batch mixes buckets"
    all_idx = sorted(i for b in batches for i in b)
    assert all_idx == list(range(8))


def test_bucketed_dataloader_limits_shapes():
    lengths = [3, 5, 9, 15, 4, 12, 7, 8] * 2
    ds = Ragged(lengths)
    bs = BucketBatchSampler(ds, batch_size=2, buckets=[8, 16],
                            size_fn=lambda i: lengths[i])
    dl = DataLoader(ds, batch_sampler=bs,
                    collate_fn=PadToBuckets([8, 16], axis=0))
    seen_shapes = set()
    total = 0
    for x, y, mask in dl:
        seen_shapes.add(tuple(x.shape[1:]))
        total += x.shape[0]
        assert tuple(mask.shape[:2]) == tuple(x.shape[:2])
    assert total == 16
    # padded feature shapes collapse to the two buckets only
    assert seen_shapes <= {(8, 4), (16, 4)}


def test_bucketing_compiles_once_per_bucket():
    import jax

    traces = []

    @jax.jit
    def step(x):
        traces.append(x.shape)
        return x.sum()

    lengths = [3, 5, 9, 15, 4, 12, 7, 8]
    ds = Ragged(lengths)
    # drop_last keeps the batch dim constant too: with bucketing this
    # bounds the number of XLA programs at #buckets
    bs = BucketBatchSampler(ds, batch_size=2, buckets=[8, 16],
                            size_fn=lambda i: lengths[i],
                            drop_last=True)
    dl = DataLoader(ds, batch_sampler=bs,
                    collate_fn=PadToBuckets([8, 16], axis=0))
    for x, y, mask in dl:
        step(x._value)
    assert len(traces) <= 2, f"recompiled per shape: {traces}"
