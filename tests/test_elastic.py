"""Elastic manager: KV registry, heartbeats, membership transitions,
and the launch controller's elastic relaunch path."""

import os
import subprocess
import sys
import tempfile
import time

import pytest

from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, KVClient, KVServer)


@pytest.fixture
def server():
    s = KVServer(ttl=1.5).start()
    yield s
    s.stop()


def test_kv_roundtrip(server):
    c = KVClient(server.endpoint)
    c.put("/foo", "bar")
    assert c.get("/foo") == "bar"
    assert c.get("/missing") is None
    c.delete("/foo")
    assert c.get("/foo") is None


def test_heartbeat_membership_and_ttl(server):
    c = KVClient(server.endpoint)
    c.heartbeat("job1/node-a", "a")
    c.heartbeat("job1/node-b", "b")
    c.heartbeat("job2/node-z", "z")
    m = c.members("job1/")
    assert sorted(m) == ["job1/node-a", "job1/node-b"]
    time.sleep(2.0)  # past ttl with no beats
    assert c.members("job1/") == {}


def test_manager_scale_down_detected(server):
    a = ElasticManager(server=server.endpoint, job_id="j", np="1:3",
                       node_id="node-a", heartbeat_interval=0.3)
    b = ElasticManager(server=server.endpoint, job_id="j", np="1:3",
                       node_id="node-b", heartbeat_interval=0.3)
    a.register()
    b.register()
    time.sleep(0.5)
    assert a.members() == ["node-a", "node-b"]
    assert a.watch() is None          # establishes baseline
    b.exit()                          # node leaves
    deadline = time.time() + 5
    ev = None
    while time.time() < deadline and ev is None:
        ev = a.watch()
        time.sleep(0.2)
    assert ev == ElasticStatus.RESTART  # still >= np_min=1
    a.exit()


def test_manager_hold_below_min(server):
    a = ElasticManager(server=server.endpoint, job_id="k", np="2:3",
                       node_id="node-a", heartbeat_interval=0.3)
    b = ElasticManager(server=server.endpoint, job_id="k", np="2:3",
                       node_id="node-b", heartbeat_interval=0.3)
    a.register()
    b.register()
    time.sleep(0.5)
    assert a.watch() is None
    b.exit()
    deadline = time.time() + 5
    ev = None
    while time.time() < deadline and ev is None:
        ev = a.watch()
        time.sleep(0.2)
    assert ev == ElasticStatus.HOLD   # dropped below np_min=2
    a.exit()


def test_manager_disabled_without_server(monkeypatch):
    monkeypatch.delenv("PADDLE_ELASTIC_SERVER", raising=False)
    m = ElasticManager(server=None)
    assert not m.enabled
    m.register()      # all no-ops
    assert m.members() == []
    assert m.watch() is None
    m.exit()


def test_launch_elastic_single_node_end_to_end(tmp_path):
    """launch --elastic_server auto runs a 1-node job to completion."""
    script = tmp_path / "train.py"
    script.write_text(
        "import os\n"
        "assert os.environ['PADDLE_TRAINERS_NUM'] == '1'\n"
        "assert 'PADDLE_MASTER' in os.environ\n"
        "print('trainer ok', os.environ['PADDLE_TRAINER_ID'])\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + \
        env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "1", "--elastic_server", "auto",
         "--log_dir", str(tmp_path / "log"), str(script)],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr
    log = (tmp_path / "log" / "workerlog.0").read_text()
    assert "trainer ok 0" in log


def test_np_max_caps_active_members(server):
    ms = [ElasticManager(server=server.endpoint, job_id="m", np="1:2",
                         node_id=f"node-{c}", heartbeat_interval=0.3)
          for c in "abc"]
    for m in ms:
        m.register()
    time.sleep(0.5)
    active = ms[0].wait_for_members(timeout=3)
    assert len(active) == 2                # capped at np_max
    assert active == ["node-a", "node-b"]  # deterministic (sorted)
    # node-c is a spare: not in active set
    assert "node-c" not in active
    for m in ms:
        m.exit()


def test_scale_up_within_bounds_triggers_restart(server):
    """A joiner within [np_min, np_max] changes the active set →
    RESTART (relaunch with the bigger world), and the job stays
    runnable throughout."""
    a = ElasticManager(server=server.endpoint, job_id="u", np="1:3",
                       node_id="node-a", heartbeat_interval=0.3)
    a.register()
    time.sleep(0.4)
    assert a.watch() is None          # baseline: just node-a
    assert a.runnable()
    b = ElasticManager(server=server.endpoint, job_id="u", np="1:3",
                       node_id="node-b", heartbeat_interval=0.3)
    b.register()                      # scale-up: 1 → 2 (within max 3)
    deadline = time.time() + 5
    ev = None
    while time.time() < deadline and ev is None:
        ev = a.watch()
        time.sleep(0.2)
    assert ev == ElasticStatus.RESTART
    assert a.runnable()
    assert a.active_members() == ["node-a", "node-b"]
    a.exit()
    b.exit()


def test_heartbeat_ttl_expiry_evicts_dead_member(server):
    """A member that stops heartbeating (process death, not graceful
    exit) must be evicted by the registry TTL and reported lost by the
    failure detector."""
    a = ElasticManager(server=server.endpoint, job_id="t", np="1:3",
                       node_id="node-a", heartbeat_interval=0.3)
    b = ElasticManager(server=server.endpoint, job_id="t", np="1:3",
                       node_id="node-b", heartbeat_interval=0.3)
    a.register()
    b.register()
    time.sleep(0.4)
    det = a.failure_detector()
    det.poll()
    assert det.alive() == ["node-a", "node-b"]
    # simulate death: stop b's heartbeat thread WITHOUT the graceful
    # registry delete that exit() performs
    b._stop.set()
    deadline = time.time() + 6        # server ttl=1.5 must lapse
    lost = []
    while time.time() < deadline and not lost:
        lost = [e for e in det.poll() if e.kind == "lost"]
        time.sleep(0.2)
    assert [e.member for e in lost] == ["node-b"]
    assert det.decide(lost) == "restart"   # 1 left >= np_min=1
    assert a.members() == ["node-a"]
    a.exit()


def test_seeded_watch_detects_spawn_window_change(server):
    a = ElasticManager(server=server.endpoint, job_id="s", np="1:3",
                       node_id="node-a", heartbeat_interval=0.3)
    a.register()
    time.sleep(0.4)
    a.seed(["node-a", "node-ghost"])  # pod spawned believing 2 members
    deadline = time.time() + 5
    ev = None
    while time.time() < deadline and ev is None:
        ev = a.watch()
        time.sleep(0.2)
    assert ev == ElasticStatus.RESTART  # ghost never appeared → restart
    a.exit()
