"""incubate fused-op API tests (upstream paddle/incubate/nn/functional/
fused_attention / fused_feedforward CUDA ops — here composed for XLA
fusion; r2 'Incubate partial' row: the 2 remaining stubs implemented)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor
from paddle_tpu.incubate.nn import functional as IF


def test_fused_feedforward_matches_manual():
    rng = np.random.RandomState(0)
    b, s, e, ff = 2, 5, 8, 16
    x = rng.randn(b, s, e).astype(np.float32)
    w1 = rng.randn(e, ff).astype(np.float32) * 0.1
    w2 = rng.randn(ff, e).astype(np.float32) * 0.1
    b1 = rng.randn(ff).astype(np.float32) * 0.1
    b2 = rng.randn(e).astype(np.float32) * 0.1
    g = np.ones(e, np.float32)
    z = np.zeros(e, np.float32)

    out = IF.fused_feedforward(
        Tensor(x), Tensor(w1), Tensor(w2), Tensor(b1), Tensor(b2),
        ln1_scale=Tensor(g), ln1_bias=Tensor(z),
        ln2_scale=Tensor(g), ln2_bias=Tensor(z),
        dropout1_rate=0.0, dropout2_rate=0.0, activation="relu",
        pre_layer_norm=True, training=True)

    # manual pre-LN composition
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ln = (x - mu) / np.sqrt(var + 1e-5)
    h = np.maximum(ln @ w1 + b1, 0.0)
    ref = x + (h @ w2 + b2)
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=1e-4,
                               atol=1e-5)


def test_fused_multi_head_attention_matches_sdpa():
    rng = np.random.RandomState(1)
    b, s, e, nh = 2, 6, 16, 4
    hd = e // nh
    x = rng.randn(b, s, e).astype(np.float32)
    qkv_w = rng.randn(3, nh, hd, e).astype(np.float32) * 0.1
    qkv_b = rng.randn(3 * nh * hd).astype(np.float32) * 0.1
    lin_w = rng.randn(e, e).astype(np.float32) * 0.1
    lin_b = rng.randn(e).astype(np.float32) * 0.1

    out = IF.fused_multi_head_attention(
        Tensor(x), Tensor(qkv_w), Tensor(lin_w), pre_layer_norm=True,
        pre_ln_scale=Tensor(np.ones(e, np.float32)),
        pre_ln_bias=Tensor(np.zeros(e, np.float32)),
        qkv_bias=Tensor(qkv_b), linear_bias=Tensor(lin_b),
        dropout_rate=0.0, attn_dropout_rate=0.0, training=True)

    # manual reference
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ln = (x - mu) / np.sqrt(var + 1e-5)
    qkv = ln @ qkv_w.reshape(3 * nh * hd, e).T + qkv_b
    qkv = qkv.reshape(b, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    qt = np.moveaxis(q, 2, 1)
    kt = np.moveaxis(k, 2, 1)
    vt = np.moveaxis(v, 2, 1)
    att = qt @ np.swapaxes(kt, -1, -2) / np.sqrt(hd)
    att = np.exp(att - att.max(-1, keepdims=True))
    att = att / att.sum(-1, keepdims=True)
    ctx = np.moveaxis(att @ vt, 1, 2).reshape(b, s, e)
    ref = x + (ctx @ lin_w + lin_b)
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=1e-4,
                               atol=1e-5)


def test_fused_mha_gradients_flow():
    rng = np.random.RandomState(2)
    b, s, e, nh = 1, 4, 8, 2
    x = Tensor(rng.randn(b, s, e).astype(np.float32))
    qkv_w = Tensor(rng.randn(3, nh, e // nh, e).astype(np.float32) * 0.1)
    lin_w = Tensor(rng.randn(e, e).astype(np.float32) * 0.1)
    for t in (x, qkv_w, lin_w):
        t.stop_gradient = False
    out = IF.fused_multi_head_attention(
        x, qkv_w, lin_w, dropout_rate=0.0, attn_dropout_rate=0.0,
        ln_scale=Tensor(np.ones(e, np.float32)),
        ln_bias=Tensor(np.zeros(e, np.float32)))
    out.sum().backward()
    for t in (x, qkv_w, lin_w):
        g = np.asarray(t.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_fused_layer_classes_train():
    """Layer wrappers (upstream incubate.nn.FusedTransformerEncoderLayer
    family) train end to end."""
    from paddle_tpu import nn, optimizer
    from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer

    paddle.seed(0)
    layer = FusedTransformerEncoderLayer(
        d_model=16, nhead=4, dim_feedforward=32, dropout_rate=0.0,
        normalize_before=True)
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=layer.parameters())
    rng = np.random.RandomState(0)
    x = Tensor(rng.randn(2, 6, 16).astype(np.float32))
    losses = []
    for _ in range(4):
        out = layer(x)
        loss = (out ** 2.0).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_lookahead_optimizer():
    """LookAhead (incubate): slow weights sync every k steps and
    training converges."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.incubate import LookAhead
    from paddle_tpu.tensor import Tensor

    paddle.seed(0)
    fc = nn.Linear(4, 1)
    inner = optimizer.SGD(learning_rate=0.1,
                          parameters=fc.parameters())
    opt = LookAhead(inner, alpha=0.5, k=3)
    rng = np.random.RandomState(0)
    X = Tensor(rng.randn(32, 4).astype(np.float32))
    Y = Tensor((rng.randn(32, 1) * 0.1 + 2.0).astype(np.float32))
    losses = []
    for i in range(30):
        loss = paddle.mean((fc(X) - Y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.2
    sd = opt.state_dict()
    assert "@LookAhead.step_count" in sd
    opt2 = LookAhead(optimizer.SGD(learning_rate=0.1,
                                   parameters=fc.parameters()), k=3)
    opt2.set_state_dict(sd)
    assert opt2._step_count == 30


def test_model_average_apply_restore():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.incubate import ModelAverage
    from paddle_tpu.tensor import Tensor

    paddle.seed(0)
    fc = nn.Linear(2, 1)
    sgd = optimizer.SGD(learning_rate=0.5,
                        parameters=fc.parameters())
    avg = ModelAverage(0.15, parameters=fc.parameters(),
                       min_average_window=2, max_average_window=10)
    X = Tensor(np.ones((4, 2), np.float32))
    Y = Tensor(np.zeros((4, 1), np.float32))
    weights = []
    for _ in range(6):
        loss = paddle.mean((fc(X) - Y) ** 2)
        loss.backward()
        sgd.step()
        sgd.clear_grad()
        avg.step()
        weights.append(fc.weight.numpy().copy())
    current = fc.weight.numpy().copy()
    # reference recomputation of the documented algorithm: running sum
    # with sliding-window decay, applied = sum / count
    ref_sum, ref_count = None, 0
    for w in weights:
        ref_sum = w if ref_sum is None else ref_sum + w
        ref_count += 1
        window = max(avg.min_window,
                     min(avg.max_window,
                         int((ref_count - 1) * avg.avg_rate) + 1))
        if ref_count > window:
            ref_sum = ref_sum * (window / ref_count)
            ref_count = window
    with avg.apply():
        applied = fc.weight.numpy().copy()
        np.testing.assert_allclose(applied, ref_sum / ref_count,
                                   rtol=1e-5)
    np.testing.assert_array_equal(fc.weight.numpy(), current)
