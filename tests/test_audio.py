"""paddle.audio features (upstream python/paddle/audio parity):
windows/mel scale vs closed forms, features vs a direct numpy
reference, MFCC orthogonal DCT."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio
from paddle_tpu.tensor import Tensor


def test_get_window_matches_numpy():
    w = audio.get_window("hann", 16, fftbins=True).numpy()
    np.testing.assert_allclose(w, np.hanning(17)[:-1], atol=1e-12)
    w2 = audio.get_window("hamming", 12, fftbins=False).numpy()
    np.testing.assert_allclose(w2, np.hamming(12), atol=1e-12)


def test_mel_scale_roundtrip_and_knots():
    for htk in (False, True):
        f = np.array([0.0, 440.0, 1000.0, 4000.0, 11025.0])
        m = audio.hz_to_mel(Tensor(f), htk=htk).numpy()
        back = audio.mel_to_hz(Tensor(m), htk=htk).numpy()
        np.testing.assert_allclose(back, f, rtol=1e-6, atol=1e-3)
    # slaney scale is linear below 1 kHz
    assert abs(audio.hz_to_mel(500.0) - 7.5) < 1e-6


def test_fbank_matrix_shape_and_partition():
    fb = audio.compute_fbank_matrix(sr=16000, n_fft=512, n_mels=40,
                                    norm=None).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # each filter is a triangle: single max, zero at edges
    assert fb[0, 0] == 0.0
    assert (fb.sum(1) > 0).all()


def test_spectrogram_matches_numpy_reference():
    sr, n_fft, hop = 8000, 256, 64
    t = np.arange(sr, dtype=np.float32) / sr
    x = np.sin(2 * np.pi * 440 * t).astype(np.float32)[None]
    spec = audio.Spectrogram(n_fft=n_fft, hop_length=hop,
                             power=2.0)(Tensor(x)).numpy()[0]
    # energy concentrates at the 440 Hz bin
    peak_bin = spec.mean(-1).argmax()
    expect = round(440 * n_fft / sr)
    assert abs(int(peak_bin) - expect) <= 1, (peak_bin, expect)


def test_mel_and_logmel_and_mfcc_shapes():
    paddle.seed(0)
    x = Tensor(np.random.RandomState(0).randn(2, 4000)
               .astype(np.float32))
    mel = audio.MelSpectrogram(sr=8000, n_fft=256, n_mels=32,
                               hop_length=128)(x)
    assert mel.shape[0] == 2 and mel.shape[1] == 32
    logmel = audio.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32,
                                     hop_length=128, top_db=80.0)(x)
    lm = logmel.numpy()
    assert np.isfinite(lm).all()
    assert lm.max() - lm.min() <= 80.0 + 1e-3
    mfcc = audio.MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32,
                      hop_length=128)(x)
    assert mfcc.shape[1] == 13


def test_create_dct_orthonormal():
    d = audio.create_dct(8, 8, norm="ortho").numpy()
    np.testing.assert_allclose(d.T @ d, np.eye(8), atol=1e-10)


def test_power_to_db_clamp():
    s = Tensor(np.array([1e-12, 1.0, 100.0], np.float64))
    db = audio.power_to_db(s, top_db=30.0).numpy()
    assert db.max() == pytest.approx(20.0)
    assert db.min() >= db.max() - 30.0 - 1e-9


def test_mel_converters_accept_lists():
    m = audio.hz_to_mel([440.0, 1000.0])
    assert m.shape == [2]
    f = audio.mel_to_hz([10.0, 25.0])
    assert f.shape == [2]
