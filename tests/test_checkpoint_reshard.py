"""Cross-topology checkpoint reshard-on-load (VERDICT r4 next #5).

Parity: upstream `python/paddle/distributed/checkpoint/` — a checkpoint
saved from one parallel topology must load into a different one, with
the framework merging/reslicing shards.  Here orbax restores each array
straight into the target topology's NamedSharding (reshard.py)."""

import os

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.distributed import collective
from paddle_tpu.distributed.runner import DistributedRunner
from paddle_tpu.distributed.checkpoint import (
    save_state_dict, load_state_dict, save_runner_state,
    load_runner_state)
from paddle_tpu.models import (gpt_tiny, GPTForCausalLM,
                               GPTPretrainingCriterion)
from paddle_tpu.tensor import Tensor

pytestmark = pytest.mark.dist


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, 256, (8, 32)).astype(np.int64)
    return [x], [np.roll(x, -1, axis=1)]


def _make_runner(mesh_axes, n_dev):
    devices = jax.devices()[:n_dev]
    mesh = collective.build_mesh(mesh_axes, devices=devices)
    collective.set_mesh(mesh)
    paddle.seed(0)
    net = GPTForCausalLM(gpt_tiny())
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    r = DistributedRunner(net, opt, GPTPretrainingCriterion(),
                          mesh=mesh)
    r.place()
    return r


def test_reshard_dp2mp2_to_dp4_and_dp1(tmp_path):
    """Train on dp2xmp2, checkpoint, resume on dp4 AND dp1: the next
    step's loss must match the source topology's next step exactly
    (same global batch, same math, different shardings)."""
    path = str(tmp_path / "ckpt")
    xs, ys = _batch(0)

    src = _make_runner({"dp": 2, "mp": 2}, 4)
    float(src.train_step(xs, ys))
    float(src.train_step(xs, ys))
    save_runner_state(src, path)
    ref_next = float(src.train_step(xs, ys))   # step 3 on source

    for axes, n in [({"dp": 4}, 4), ({"dp": 1}, 1)]:
        dst = _make_runner(axes, n)
        load_runner_state(dst, path)
        got = float(dst.train_step(xs, ys))    # step 3 resumed
        assert abs(got - ref_next) < 1e-3, \
            f"resume on {axes}: loss {got} != source-next {ref_next}"
        assert dst.optimizer._global_step >= 2


def test_reshard_changes_actual_sharding(tmp_path):
    """The loaded arrays live in the TARGET sharding (not a replicated
    host-gather): a dp4-sharding-4 ZeRO runner's moment slots end up
    sharded over 4 devices after loading a dp2xmp2 checkpoint."""
    path = str(tmp_path / "ckpt")
    src = _make_runner({"dp": 2, "mp": 2}, 4)
    float(src.train_step(*_batch(0)))
    save_runner_state(src, path)

    devices = jax.devices()[:4]
    mesh = collective.build_mesh({"sharding": 4}, devices=devices)
    collective.set_mesh(mesh)
    paddle.seed(0)
    net = GPTForCausalLM(gpt_tiny())
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    dst = DistributedRunner(net, opt, GPTPretrainingCriterion(),
                            mesh=mesh, sharding_stage=2)
    dst.place()
    load_runner_state(dst, path)
    # ZeRO-2: at least one moment slot should be sharded (not
    # single-device) across the 4 'sharding' devices
    sharded = 0
    for st in dst._opt_state.values():
        for v in st.values():
            if hasattr(v, "sharding") and len(v.sharding.device_set) == 4:
                sharded += 1
    assert sharded > 0, "no optimizer slot is sharded over the target mesh"
    got = float(dst.train_step(*_batch(0)))
    assert np.isfinite(got)


def test_save_load_state_dict_plain_tree(tmp_path):
    """Module-level API on a plain tree of sharded Tensors."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    devices = jax.devices()[:4]
    mesh = collective.build_mesh({"dp": 4}, devices=devices)
    w = np.arange(32, dtype=np.float32).reshape(8, 4)
    sharded = jax.device_put(w, NamedSharding(mesh, P("dp", None)))
    sd = {"w": Tensor(sharded), "b": Tensor(np.ones(4, np.float32))}
    save_state_dict(sd, str(tmp_path / "sd"))

    mesh2 = collective.build_mesh({"dp": 2}, devices=devices[:2])
    tgt = {"w": Tensor(jax.device_put(
        np.zeros((8, 4), np.float32),
        NamedSharding(mesh2, P(None, "dp")))),
        "b": Tensor(np.zeros(4, np.float32))}
    load_state_dict(tgt, str(tmp_path / "sd"))
    np.testing.assert_allclose(tgt["w"].numpy(), w)
    np.testing.assert_allclose(tgt["b"].numpy(), np.ones(4))
    # target sharding honored: column-sharded over 2 devices
    assert len(tgt["w"]._value.sharding.device_set) == 2
