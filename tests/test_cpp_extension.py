"""paddle.utils.cpp_extension — custom C++ host operators compiled with
g++ and stitched into XLA programs as host callbacks (upstream
python/paddle/utils/cpp_extension/ custom-op toolchain, TPU-native
design: host op = pure_callback; device kernels are Pallas)."""

import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor
from paddle_tpu.utils import cpp_extension

RELU2_SRC = textwrap.dedent("""
    #include <cstdint>

    extern "C" void relu2(const float** ins, const int64_t** shapes,
                          const int32_t* ndims, int32_t n_ins,
                          float* out, const int64_t* out_shape,
                          int32_t out_ndim) {
        int64_t n = 1;
        for (int32_t i = 0; i < out_ndim; ++i) n *= out_shape[i];
        const float* x = ins[0];
        for (int64_t i = 0; i < n; ++i) {
            float v = x[i] > 0.f ? x[i] : 0.f;
            out[i] = v * v;
        }
    }

    extern "C" void relu2_grad(const float** ins,
                               const int64_t** shapes,
                               const int32_t* ndims, int32_t n_ins,
                               const float* grad_out,
                               const int64_t* gout_shape,
                               int32_t gout_ndim, float** grad_ins) {
        int64_t n = 1;
        for (int32_t i = 0; i < gout_ndim; ++i) n *= gout_shape[i];
        const float* x = ins[0];
        float* gx = grad_ins[0];
        for (int64_t i = 0; i < n; ++i)
            gx[i] = x[i] > 0.f ? 2.f * x[i] * grad_out[i] : 0.f;
    }

    extern "C" void pairwise_mul(const float** ins,
                                 const int64_t** shapes,
                                 const int32_t* ndims, int32_t n_ins,
                                 float* out, const int64_t* out_shape,
                                 int32_t out_ndim) {
        int64_t n = 1;
        for (int32_t i = 0; i < out_ndim; ++i) n *= out_shape[i];
        for (int64_t i = 0; i < n; ++i) out[i] = ins[0][i] * ins[1][i];
    }
""")


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    bdir = str(tmp_path_factory.mktemp("ext"))
    return cpp_extension.load_inline("testext", RELU2_SRC,
                                     build_directory=bdir)


def test_forward_eager_matches_numpy(ext):
    relu2 = ext.def_op("relu2", grad_symbol="relu2_grad")
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype(np.float32)
    y = relu2(Tensor(x))
    np.testing.assert_allclose(np.asarray(y.numpy()),
                               np.maximum(x, 0) ** 2, rtol=1e-6)


def test_backward_through_tape(ext):
    relu2 = ext.def_op("relu2", grad_symbol="relu2_grad")
    rng = np.random.RandomState(1)
    x = Tensor(rng.randn(3, 3).astype(np.float32))
    x.stop_gradient = False
    y = relu2(x)
    y.sum().backward()
    xv = np.asarray(x.numpy())
    expect = np.where(xv > 0, 2 * xv, 0.0)
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), expect,
                               rtol=1e-6)


def test_under_jit_and_to_static(ext):
    import jax
    relu2 = ext.def_op("relu2", grad_symbol="relu2_grad")

    @paddle.jit.to_static
    def f(a):
        return relu2(a) + 1.0

    rng = np.random.RandomState(2)
    x = rng.randn(2, 8).astype(np.float32)
    out = f(Tensor(x))
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.maximum(x, 0) ** 2 + 1.0, rtol=1e-6)
    # grad under jax.jit through the custom vjp
    g = jax.jit(jax.grad(lambda v: relu2.raw(v).sum()))(x)
    np.testing.assert_allclose(np.asarray(g),
                               np.where(x > 0, 2 * x, 0.0), rtol=1e-6)


def test_multi_input_op(ext):
    mul = ext.def_op("pairwise_mul")
    rng = np.random.RandomState(3)
    a = rng.randn(6).astype(np.float32)
    b = rng.randn(6).astype(np.float32)
    out = mul(Tensor(a), Tensor(b))
    np.testing.assert_allclose(np.asarray(out.numpy()), a * b, rtol=1e-6)


def test_build_cache_and_errors(ext, tmp_path):
    # same source: cached .so reused (content-hash name exists once)
    so1 = ext.so_path
    ext2 = cpp_extension.load_inline("testext", RELU2_SRC,
                                     build_directory=os.path.dirname(so1))
    assert ext2.so_path == so1
    # unknown symbol fails loudly
    with pytest.raises(AttributeError, match="no symbol"):
        ext.def_op("nope")
    # broken source reports the compiler error
    with pytest.raises(RuntimeError, match="build of"):
        cpp_extension.load_inline("bad", "not c++ at all",
                                  build_directory=str(tmp_path))


def test_trains_inside_model_step(ext):
    """The custom op participates in a real optimization loop."""
    from paddle_tpu import nn, optimizer
    relu2 = ext.def_op("relu2", grad_symbol="relu2_grad")
    paddle.seed(0)
    lin = nn.Linear(4, 4)
    opt = optimizer.SGD(0.1, parameters=lin.parameters())
    rng = np.random.RandomState(4)
    x = Tensor(rng.rand(8, 4).astype(np.float32))
    first = None
    for _ in range(20):
        loss = relu2(lin(x)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss.numpy())
    assert float(loss.numpy()) < 0.5 * first
