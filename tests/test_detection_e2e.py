"""PP-YOLOE-class detector end-to-end (VERDICT r3 next #3 /
BASELINE.json config 5): assemble backbone+neck+head, train on bucketed
dynamic-shape batches with padded gt boxes, loss must decrease; eval
path produces NMS'd detections."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.tensor import Tensor
from paddle_tpu.vision.models.ppyoloe import (
    PPYOLOE, ppyoloe_tiny, ppyoloe_crn_s, task_aligned_assign,
    _make_anchors, _pairwise_iou, _giou)

pytestmark = pytest.mark.slow


def _synth_batch(rng, B, size, num_classes=4, gmax=3):
    """Images with colored rectangles; gt = the rectangles."""
    imgs = np.zeros((B, 3, size, size), np.float32)
    boxes = np.zeros((B, gmax, 4), np.float32)
    labels = np.zeros((B, gmax), np.int64)
    mask = np.zeros((B, gmax), np.float32)
    for b in range(B):
        n = rng.randint(1, gmax + 1)
        for g in range(n):
            w, h = rng.randint(size // 4, size // 2, 2)
            x1 = rng.randint(0, size - w)
            y1 = rng.randint(0, size - h)
            c = rng.randint(0, num_classes)
            imgs[b, c % 3, y1:y1 + h, x1:x1 + w] = 1.0
            boxes[b, g] = [x1, y1, x1 + w, y1 + h]
            labels[b, g] = c
            mask[b, g] = 1.0
    return imgs, boxes, labels, mask


def test_tal_assigner_dense_contract():
    """Dense TAL: positives only inside valid gt boxes; padded gts
    never assigned."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    points, stride = _make_anchors([(8, 8), (4, 4)], [8, 16])
    A = points.shape[0]
    B, G, C = 2, 3, 4
    scores = jnp.asarray(rng.rand(B, A, C).astype(np.float32)) * 0.5
    pred = jnp.concatenate([points - 8.0, points + 8.0], -1)[None] \
        .repeat(B, 0)
    gt = jnp.asarray([[[0, 0, 32, 32], [40, 40, 64, 64], [0, 0, 0, 0]],
                      [[8, 8, 56, 56], [0, 0, 0, 0], [0, 0, 0, 0]]],
                     jnp.float32)
    lbl = jnp.asarray([[1, 2, 0], [3, 0, 0]], jnp.int32)
    msk = jnp.asarray([[1, 1, 0], [1, 0, 0]], jnp.float32)
    pos, agt, ascore, _ = task_aligned_assign(
        scores, pred, points, gt, lbl, msk)
    pos = np.asarray(pos)
    agt = np.asarray(agt)
    assert pos.any(), "no positives assigned"
    # a positive anchor's center must lie inside its assigned gt
    pts = np.asarray(points)
    for b in range(B):
        for a in np.where(pos[b])[0]:
            g = agt[b, a]
            assert msk[b, g] == 1.0, "padded gt assigned"
            x, y = pts[a]
            x1, y1, x2, y2 = np.asarray(gt)[b, g]
            assert x1 <= x <= x2 and y1 <= y <= y2
    assert (np.asarray(ascore) >= 0).all()
    assert np.asarray(ascore)[~pos.astype(bool)].max() == 0.0


def test_detector_builds_and_eval_shapes():
    paddle.seed(0)
    net = ppyoloe_tiny(num_classes=4)
    net.eval()
    x = Tensor(np.random.RandomState(0).rand(1, 3, 64, 64)
               .astype(np.float32))
    scores, boxes = net(x)
    A = 8 * 8 + 4 * 4 + 2 * 2
    assert scores.shape == [1, A, 4]
    assert boxes.shape == [1, A, 4]
    outs = net.postprocess(scores, boxes, score_threshold=0.0,
                           keep_top_k=10)
    assert len(outs) == 1 and outs[0].shape[1] == 6


def test_detector_trains_loss_decreases_bucketed():
    """One compiled program per image-size bucket (64 and 96); loss
    decreases >40% over a short schedule."""
    paddle.seed(0)
    rng = np.random.RandomState(0)
    net = ppyoloe_tiny(num_classes=4)
    net.train()
    opt = optimizer.Adam(learning_rate=5e-3,
                         parameters=net.parameters())
    batches = {64: _synth_batch(rng, 2, 64),
               96: _synth_batch(rng, 2, 96)}
    first_by_bucket, last_by_bucket = {}, {}
    for step in range(30):
        size = (64, 96)[step % 2]   # bucketed dynamic shapes
        imgs, boxes, labels, mask = batches[size]
        out = net(Tensor(imgs), gt_boxes=Tensor(boxes),
                  gt_labels=Tensor(labels), gt_mask=Tensor(mask))
        loss = out["loss"]
        loss.backward()
        opt.step()
        opt.clear_grad()
        lv = float(loss.numpy())
        assert np.isfinite(lv), f"loss blew up at step {step}"
        first_by_bucket.setdefault(size, lv)
        last_by_bucket[size] = lv
    for size in (64, 96):
        assert last_by_bucket[size] < 0.5 * first_by_bucket[size], (
            f"bucket {size}: {first_by_bucket[size]} -> "
            f"{last_by_bucket[size]}")


def test_detector_jit_train_step_compiles_once_per_bucket():
    """The whole train step (assignment + losses included) is
    jittable — the TPU-first design claim of the module header."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn import functional_call as F

    paddle.seed(1)
    net = ppyoloe_tiny(num_classes=4)
    net.train()
    rng = np.random.RandomState(1)

    compiles = []

    @jax.jit
    def loss_only(params, frozen, buffers, imgs, boxes, labels, mask):
        compiles.append(1)
        with F.bind(net, params, buffers, frozen):
            out = net(Tensor(imgs), gt_boxes=Tensor(boxes),
                      gt_labels=Tensor(labels), gt_mask=Tensor(mask))
        return out["loss"]._value

    params = F.param_dict(net)
    frozen = F.frozen_dict(net)
    buffers = F.buffer_dict(net)
    for step in range(4):
        imgs, boxes, labels, mask = _synth_batch(rng, 2, 64)
        lv = loss_only(params, frozen, buffers, imgs, boxes, labels,
                       mask)
    assert np.isfinite(float(lv))
    assert len(compiles) == 1, "train step retraced per call"


def test_ppyoloe_s_factory():
    net = ppyoloe_crn_s(num_classes=10)
    assert len(list(net.parameters())) > 50


def test_detector_trains_to_nonzero_ap():
    """VERDICT r4 next #8: train on a fixed synthetic labeled set, then
    run the FULL eval path (forward -> postprocess -> multiclass_nms ->
    AP@0.5).  The synthetic task (solid rectangles, class = fill
    channel) is learnable; AP must rise well above chance."""
    from paddle_tpu.vision.detection_eval import eval_detections_ap

    paddle.seed(0)
    rng = np.random.RandomState(0)
    C = 3
    net = ppyoloe_tiny(num_classes=C)
    net.train()
    opt = optimizer.Adam(learning_rate=5e-3,
                         parameters=net.parameters())
    train = [_synth_batch(rng, 2, 64, num_classes=C) for _ in range(4)]
    for step in range(48):
        imgs, boxes, labels, mask = train[step % len(train)]
        out = net(Tensor(imgs), gt_boxes=Tensor(boxes),
                  gt_labels=Tensor(labels), gt_mask=Tensor(mask))
        out["loss"].backward()
        opt.step()
        opt.clear_grad()

    # eval on the SAME distribution (toy capacity net): e2e NMS path
    net.eval()
    dets, gtb, gtl = [], [], []
    for imgs, boxes, labels, mask in train:
        scores, pboxes = net(Tensor(imgs))
        outs = net.postprocess(scores, pboxes, score_threshold=0.05,
                               nms_threshold=0.6)
        for b in range(imgs.shape[0]):
            det = outs[b]
            det = det.numpy() if hasattr(det, "numpy") else np.asarray(det)
            dets.append(det)
            valid = mask[b] > 0
            gtb.append(boxes[b][valid])
            gtl.append(labels[b][valid])
    res = eval_detections_ap(dets, gtb, gtl, num_classes=C,
                             iou_threshold=0.5)
    assert res["map"] > 0.25, \
        f"mAP@0.5 {res['map']:.3f} too low; per-class {res['ap_per_class']}"


def test_eval_detections_ap_oracle():
    """AP utility sanity: perfect detections -> AP 1; shifted boxes at
    low IoU -> AP 0; one FP halves precision but not the envelope."""
    from paddle_tpu.vision.detection_eval import eval_detections_ap

    gt = [np.array([[10, 10, 30, 30], [40, 40, 60, 60]], np.float32)]
    gl = [np.array([0, 1])]
    perfect = [np.array([[0, 0.9, 10, 10, 30, 30],
                         [1, 0.8, 40, 40, 60, 60]], np.float32)]
    assert eval_detections_ap(perfect, gt, gl, 2)["map"] == 1.0

    missed = [np.array([[0, 0.9, 100, 100, 120, 120],
                        [1, 0.8, 200, 200, 220, 220]], np.float32)]
    assert eval_detections_ap(missed, gt, gl, 2)["map"] == 0.0

    with_fp = [np.array([[0, 0.9, 10, 10, 30, 30],
                         [0, 0.5, 100, 100, 120, 120],
                         [1, 0.8, 40, 40, 60, 60]], np.float32)]
    r = eval_detections_ap(with_fp, gt, gl, 2)
    assert r["ap_per_class"][0] == 1.0  # FP ranked below the TP
    assert r["map"] == 1.0
