"""Speculative decoding tests (ISSUE 19): draft/verify/accept-reject
inside the ONE compiled decode program.

Exactness contract under test (DESIGN-SERVING.md §Speculative tier):
a proposal is accepted only when it EQUALS the target's own
deterministic sampling choice at that position, so the emitted
sequence is token-identical to the sequential oracle — under greedy
AND under seeded sampling, for ANY draft (a bad draft only lowers the
accept rate, never changes a token).  The single-trace pin, the
k-page admission envelope, and composition with the prefix cache /
chunked prefill / disaggregation all ride along.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle

# retrace sentinel armed module-wide: any trace of a single-trace
# compiled entry after its first dispatch raises, making every
# recompile pin in here an ambient property
pytestmark = pytest.mark.usefixtures("retrace_strict")

import jax  # noqa: E402


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    """This jaxlib's CPU client segfaults inside
    ``backend.deserialize_executable`` when, late in a full-suite run,
    a compile in this module hits a persistent-cache entry written
    earlier in the same process (observed deterministically at
    sample_tokens' lax.cond with a cold cache dir, so it is not a
    corrupt entry — it is the deserialize path itself).  Compile these
    tests fresh; the module's programs are tiny and the rest of the
    suite keeps the conftest cache."""
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)

from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.inference.serving import (
    DecodeEngine, LLMServer, SPEC_SENTINEL, ServingModelConfig,
    extract_decode_params, filter_spec_stream, reference_decode)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def nets():
    """(target, adversarial draft, gpt config): same geometry,
    different weights — the draft proposes near-garbage, which is
    exactly what the exactness contract must shrug off."""
    paddle.seed(0)
    cfg = gpt_tiny(use_flash_attention=False)
    target = GPTForCausalLM(cfg)
    target.eval()
    paddle.seed(7)
    adversary = GPTForCausalLM(cfg)
    adversary.eval()
    return target, adversary, cfg


def _oracle(net, cfg):
    params = extract_decode_params(net)
    scfg = ServingModelConfig.from_gpt_config(cfg)

    def ref(prompt, n, **kw):
        toks, _ = reference_decode(params, scfg, prompt, n, **kw)
        return [int(t) for t in toks]
    return ref


# ---------------------------------------------------------------------------
# exactness: greedy and seeded, self-draft and adversarial
# ---------------------------------------------------------------------------
def test_spec_greedy_token_identity_vs_oracle(nets):
    """THE acceptance pin: mixed-length speculative decode (self-draft,
    accept ≈ 1) = per-request sequential dense decode, token for
    token — including a request whose max_tokens truncates inside a
    speculative window."""
    net, _, cfg = nets
    ref = _oracle(net, cfg)
    eng = DecodeEngine(net, max_batch=4, block_size=8, num_blocks=64,
                       draft=net, spec_k=4)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).tolist()
               for n in (5, 11, 3, 17)]
    lens = (12, 3, 5, 9)               # 3 lands mid-window at k=4
    futs = [eng.submit(p, max_tokens=n).future
            for p, n in zip(prompts, lens)]
    eng.run_until_idle()
    for p, n, f in zip(prompts, lens, futs):
        got = f.result(timeout=0).tokens
        assert got == ref(p, n)
    assert eng.compile_stats()["decode_traces"] == 1
    st = eng._kv.allocator.stats()
    assert st["allocated"] == 0 and st["reserved"] == 0
    spec = eng.stats()["spec"]
    assert spec["k"] == 4 and spec["dispatches"] > 0
    assert 0.0 <= spec["accept_rate"] <= 1.0


def test_spec_adversarial_draft_still_token_exact(nets):
    """A draft with unrelated weights proposes mostly-rejected tokens:
    throughput degrades toward one token per dispatch, correctness
    does not budge."""
    net, adversary, cfg = nets
    ref = _oracle(net, cfg)
    eng = DecodeEngine(net, max_batch=2, block_size=8, num_blocks=64,
                       draft=adversary, spec_k=4)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).tolist()
               for n in (6, 13)]
    futs = [eng.submit(p, max_tokens=10).future for p in prompts]
    eng.run_until_idle()
    for p, f in zip(prompts, futs):
        assert f.result(timeout=0).tokens == ref(p, 10)
    assert eng.compile_stats()["decode_traces"] == 1
    # rejections never commit look-ahead writes: pool fully reclaimed
    st = eng._kv.allocator.stats()
    assert st["allocated"] == 0 and st["reserved"] == 0


def test_spec_seeded_sampling_matches_oracle(nets):
    """Distribution-exactness pin: seeded sampled requests reproduce
    the sequential oracle token for token THROUGH the speculative
    window (same ``fold_in(seed, position)`` keys, and the accept rule
    compares against the target's own sampled choice) — with a
    self-draft and with an adversarial draft."""
    net, adversary, cfg = nets
    ref = _oracle(net, cfg)
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, cfg.vocab_size, (9,)).tolist()
    kw = dict(temperature=0.9, top_k=12, top_p=0.85, seed=42)
    want = ref(prompt, 11, **kw)
    for draft in (net, adversary):
        eng = DecodeEngine(net, max_batch=2, block_size=8,
                           num_blocks=64, draft=draft, spec_k=4)
        fut = eng.submit(prompt, max_tokens=11, **kw).future
        eng.run_until_idle()
        assert fut.result(timeout=0).tokens == want


def test_spec_mixed_greedy_and_sampled_batch(nets):
    """Greedy and sampled requests share one speculative batch (the
    sampling vectors are [B] data): each matches its own oracle."""
    net, _, cfg = nets
    ref = _oracle(net, cfg)
    eng = DecodeEngine(net, max_batch=3, block_size=8, num_blocks=64,
                       draft=net, spec_k=3)
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).tolist()
               for n in (4, 8, 6)]
    kws = [dict(), dict(temperature=1.1, seed=5),
           dict(temperature=0.7, top_k=8, seed=9)]
    futs = [eng.submit(p, max_tokens=7, **kw).future
            for p, kw in zip(prompts, kws)]
    eng.run_until_idle()
    for p, kw, f in zip(prompts, kws, futs):
        assert f.result(timeout=0).tokens == ref(p, 7, **kw)
    assert eng.compile_stats()["decode_traces"] == 1


def test_spec_eos_truncates_mid_window(nets):
    """EOS emitted inside a speculative window: the result truncates
    at (and includes) eos, and the device-side done mask frees the
    slot before max_tokens."""
    net, _, cfg = nets
    prompt = list(range(3, 9))
    ref = _oracle(net, cfg)
    toks = ref(prompt, 10)
    eos = toks[4]
    cut = toks.index(eos)
    eng = DecodeEngine(net, max_batch=1, block_size=8, num_blocks=64,
                       draft=net, spec_k=4, eos_id=eos,
                       done_poll_interval=2)
    fut = eng.submit(prompt, 10).future
    eng.run_until_idle()
    got = fut.result(timeout=0).tokens
    assert got == toks[:cut + 1] and got[-1] == eos
    assert eng.active_count == 0


# ---------------------------------------------------------------------------
# continuous batching: join/leave, single trace
# ---------------------------------------------------------------------------
def test_spec_join_leave_across_groups_zero_recompiles(nets):
    net, _, cfg = nets
    ref = _oracle(net, cfg)
    eng = DecodeEngine(net, max_batch=2, block_size=8, num_blocks=64,
                       draft=net, spec_k=4)
    rng = np.random.RandomState(5)

    def run_some(n):
        for _ in range(n):
            if not eng.step():
                break

    p1 = rng.randint(0, 256, (5,)).tolist()
    p2 = rng.randint(0, 256, (9,)).tolist()
    f1 = eng.submit(p1, 4).future
    f2 = eng.submit(p2, 10).future
    run_some(4)
    assert eng.compile_stats()["decode_traces"] == 1
    p3 = rng.randint(0, 256, (12,)).tolist()
    f3 = eng.submit(p3, 6).future
    p4 = rng.randint(0, 256, (3,)).tolist()
    f4 = eng.submit(p4, 8).future
    eng.run_until_idle()
    for p, n, f in ((p1, 4, f1), (p2, 10, f2), (p3, 6, f3),
                    (p4, 8, f4)):
        assert f.result(timeout=0).tokens == ref(p, n)
    assert eng.compile_stats()["decode_traces"] == 1
    st = eng._kv.allocator.stats()
    assert st["allocated"] == 0 and st["reserved"] == 0


# ---------------------------------------------------------------------------
# k-page admission envelope
# ---------------------------------------------------------------------------
def test_spec_admission_reserves_k_lookahead(nets):
    """The worst-case envelope grows by k look-ahead positions: a
    request the classic engine admits at the pool boundary is refused
    by the speculative door (its uncommitted window writes could
    outrun the allocation)."""
    net, _, cfg = nets
    prompt = list(range(1, 9))                    # 8 tokens
    # need = 8 + 9 - 1 = 16 positions = 2 blocks: exactly capacity
    plain = DecodeEngine(net, max_batch=1, block_size=8, num_blocks=3)
    plain.submit(prompt, 9)
    spec = DecodeEngine(net, max_batch=1, block_size=8, num_blocks=3,
                        draft=net, spec_k=4)
    assert spec.scheduler.lookahead == 4
    with pytest.raises(ValueError):
        spec.submit(prompt, 9)                    # 16 + 4 > 2 blocks


def test_spec_no_oom_under_rejection_churn(nets):
    """Adversarial draft on a tight pool: maximum rejection churn
    (every window re-writes look-ahead positions that never commit)
    crosses block boundaries for many requests without ever taking a
    hot-loop allocation failure, and the pool drains clean."""
    net, adversary, cfg = nets
    ref = _oracle(net, cfg)
    eng = DecodeEngine(net, max_batch=2, block_size=8, num_blocks=12,
                       draft=adversary, spec_k=4, max_queue=8)
    rng = np.random.RandomState(6)
    jobs = []
    for n in (5, 11, 7, 3):
        p = rng.randint(0, cfg.vocab_size, (n,)).tolist()
        jobs.append((p, eng.submit(p, max_tokens=9).future))
    eng.run_until_idle()
    for p, f in jobs:
        assert f.result(timeout=0).tokens == ref(p, 9)
    st = eng._kv.allocator.stats()
    assert st["allocated"] == 0 and st["reserved"] == 0


# ---------------------------------------------------------------------------
# composition: prefix cache, chunked prefill, disaggregation
# ---------------------------------------------------------------------------
def test_spec_composes_with_prefix_cache_and_chunked_prefill(nets):
    net, _, cfg = nets
    ref = _oracle(net, cfg)
    eng = DecodeEngine(net, max_batch=2, block_size=8, num_blocks=64,
                       draft=net, spec_k=4, prefix_cache=True,
                       prefill_chunk=8)
    rng = np.random.RandomState(7)
    shared = rng.randint(0, cfg.vocab_size, (16,)).tolist()
    a = shared + rng.randint(0, cfg.vocab_size, (5,)).tolist()
    b = shared + rng.randint(0, cfg.vocab_size, (3,)).tolist()
    fa = eng.submit(a, max_tokens=8).future
    eng.run_until_idle()
    fb = eng.submit(b, max_tokens=8).future       # prefix now cached
    eng.run_until_idle()
    assert fa.result(timeout=0).tokens == ref(a, 8)
    assert fb.result(timeout=0).tokens == ref(b, 8)
    assert eng._prefix.stats()["hits"] > 0
    assert eng.compile_stats()["decode_traces"] == 1


def test_spec_composes_with_disagg_handoff(nets):
    """Prefill-role replica (no draft — speculation lives with the
    decode program) hands a migrated request to a speculative
    decode-role replica: token-exact end to end."""
    net, _, cfg = nets
    ref = _oracle(net, cfg)
    pre = DecodeEngine(net, max_batch=2, block_size=8, num_blocks=64,
                       role="prefill")
    dec = DecodeEngine(net, max_batch=2, block_size=8, num_blocks=64,
                       role="decode", draft=net, spec_k=4)
    rng = np.random.RandomState(8)
    prompt = rng.randint(0, cfg.vocab_size, (11,)).tolist()
    fut = pre.submit(prompt, max_tokens=9).future
    for _ in range(200):
        busy = pre.step()
        for mig in pre.pop_ready_migrations():
            dec.submit_migration(mig)
        if not busy:
            break
    dec.run_until_idle()
    assert fut.result(timeout=0).tokens == ref(prompt, 9)
    for e in (pre, dec):
        st = e._kv.allocator.stats()
        assert st["allocated"] == 0 and st["reserved"] == 0


# ---------------------------------------------------------------------------
# stream-out: sentinel contract
# ---------------------------------------------------------------------------
def test_filter_spec_stream_drops_sentinels_and_renumbers():
    seen = []
    wrapped = filter_spec_stream(
        lambda rid, idx, tok: seen.append((rid, idx, tok)),
        max_tokens=4)
    feed = [3, SPEC_SENTINEL, 5, SPEC_SENTINEL, SPEC_SENTINEL,
            7, 9, 11]                      # 11 overshoots max_tokens
    for i, t in enumerate(feed):
        wrapped(1, i, t)
    assert seen == [(1, 0, 3), (1, 1, 5), (1, 2, 7), (1, 3, 9)]


def test_spec_stream_matches_result(nets):
    """End-to-end lazy stream through the filter: dense in-order
    indices, no sentinels, token values equal to the final result."""
    net, _, cfg = nets
    eng = DecodeEngine(net, max_batch=1, block_size=8, num_blocks=64,
                       draft=net, spec_k=4)
    got = []
    cb = filter_spec_stream(
        lambda rid, idx, tok: got.append((idx, tok)), max_tokens=9)
    fut = eng.submit(list(range(2, 9)), max_tokens=9,
                     stream_cb=cb).future
    eng.run_until_idle()
    toks = fut.result(timeout=0).tokens
    assert [i for i, _ in got] == list(range(len(toks)))
    assert [t for _, t in got] == toks
    assert SPEC_SENTINEL not in toks


# ---------------------------------------------------------------------------
# configuration surface and refusals
# ---------------------------------------------------------------------------
def test_spec_refusals(nets):
    net, _, cfg = nets
    with pytest.raises(ValueError, match="prefill-role"):
        DecodeEngine(net, role="prefill", draft=net)
    with pytest.raises(ValueError, match="spec_k"):
        DecodeEngine(net, spec_k=4)           # no proposal model
    with pytest.raises(ValueError, match="spec_k"):
        DecodeEngine(net, draft=net, spec_k=0)
    paddle.seed(11)
    other = GPTForCausalLM(gpt_tiny(use_flash_attention=False,
                                    num_hidden_layers=1))
    other.eval()
    with pytest.raises(ValueError, match="geometry"):
        DecodeEngine(net, draft=other)


def test_spec_k_env_knob(nets, monkeypatch):
    net, _, cfg = nets
    monkeypatch.setenv("PADDLE_TPU_SPEC_K", "2")
    eng = DecodeEngine(net, max_batch=1, block_size=8, num_blocks=32,
                       draft=net)
    assert eng.spec_k == 2
    plain = DecodeEngine(net, max_batch=1, block_size=8,
                         num_blocks=32)
    assert plain.spec_k == 0                  # knob alone never arms


def test_spec_metrics_registered(nets):
    net, _, cfg = nets
    eng = DecodeEngine(net, max_batch=1, block_size=8, num_blocks=64,
                       draft=net, spec_k=4)
    fut = eng.submit(list(range(1, 7)), max_tokens=8).future
    eng.run_until_idle()
    assert fut.result(timeout=0)
    assert eng._c_spec_dispatches.collect() > 0
    assert eng._h_spec_tpd.collect()["count"] > 0
    from paddle_tpu import observability as obs
    text = obs.scrape_prometheus()
    for name in ("serving_spec_dispatches_total",
                 "serving_spec_tokens_per_dispatch",
                 "serving_spec_accept_rate"):
        assert name in text


# ---------------------------------------------------------------------------
# multi-process end to end
# ---------------------------------------------------------------------------
_E2E = """
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.inference.serving import (LLMServer,
                                          extract_decode_params,
                                          reference_decode,
                                          ServingModelConfig)
paddle.seed(0)
cfg = gpt_tiny(use_flash_attention=False)
net = GPTForCausalLM(cfg); net.eval()
paddle.seed(7)
draft = GPTForCausalLM(cfg); draft.eval()
srv = LLMServer(net, max_batch=2, block_size=8, num_blocks=64,
                draft=draft, spec_k=4, auto_start=False)
srv.warmup([8]); srv.start()
rng = np.random.RandomState(1)
prompts = [rng.randint(0, cfg.vocab_size, (n,)).tolist()
           for n in (6, 14)]
res = [srv.submit(p, 10).result(timeout=240) for p in prompts]
srv.close()
params = extract_decode_params(net)
scfg = ServingModelConfig.from_gpt_config(cfg)
for p, r in zip(prompts, res):
    ref, _ = reference_decode(params, scfg, p, 10)
    assert r.tokens == [int(t) for t in ref], (p, r.tokens)
print("SPEC-E2E-OK")
"""


@pytest.mark.slow
def test_spec_server_multiprocess_e2e():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", _E2E], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SPEC-E2E-OK" in r.stdout
