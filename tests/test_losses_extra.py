"""Extra loss coverage (upstream test/legacy_test/test_*_loss.py
analogs) — torch is the independent numerics oracle, incl. CTC."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F
from paddle_tpu.tensor import Tensor


def _t(x):
    import torch
    return torch.tensor(np.asarray(x))


def test_huber_loss_matches_torch():
    import torch
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype(np.float32) * 2
    y = rng.randn(4, 5).astype(np.float32)
    got = nn.HuberLoss(delta=1.3)(Tensor(x), Tensor(y))
    exp = torch.nn.HuberLoss(delta=1.3)(_t(x), _t(y))
    np.testing.assert_allclose(float(got.numpy()), float(exp),
                               rtol=1e-5)


def test_soft_margin_and_multilabel_match_torch():
    import torch
    rng = np.random.RandomState(1)
    x = rng.randn(4, 6).astype(np.float32)
    y = np.sign(rng.randn(4, 6)).astype(np.float32)
    got = nn.SoftMarginLoss()(Tensor(x), Tensor(y))
    exp = torch.nn.SoftMarginLoss()(_t(x), _t(y))
    np.testing.assert_allclose(float(got.numpy()), float(exp),
                               rtol=1e-5)
    yb = (y > 0).astype(np.float32)
    got2 = nn.MultiLabelSoftMarginLoss()(Tensor(x), Tensor(yb))
    exp2 = torch.nn.MultiLabelSoftMarginLoss()(_t(x), _t(yb))
    np.testing.assert_allclose(float(got2.numpy()), float(exp2),
                               rtol=1e-5)


def test_poisson_and_gaussian_nll_match_torch():
    import torch
    rng = np.random.RandomState(2)
    x = rng.randn(8).astype(np.float32)
    y = rng.poisson(2.0, 8).astype(np.float32)
    got = nn.PoissonNLLLoss(full=True)(Tensor(x), Tensor(y))
    exp = torch.nn.PoissonNLLLoss(full=True)(_t(x), _t(y))
    np.testing.assert_allclose(float(got.numpy()), float(exp),
                               rtol=1e-4)
    var = np.abs(rng.randn(8).astype(np.float32)) + 0.1
    tgt = rng.randn(8).astype(np.float32)
    got2 = nn.GaussianNLLLoss(full=True)(Tensor(x), Tensor(tgt),
                                         Tensor(var))
    exp2 = torch.nn.GaussianNLLLoss(full=True)(_t(x), _t(tgt), _t(var))
    np.testing.assert_allclose(float(got2.numpy()), float(exp2),
                               rtol=1e-4)


def test_triplet_margin_loss_matches_torch():
    import torch
    rng = np.random.RandomState(3)
    a = rng.randn(5, 8).astype(np.float32)
    p = rng.randn(5, 8).astype(np.float32)
    n = rng.randn(5, 8).astype(np.float32)
    for swap in (False, True):
        got = nn.TripletMarginLoss(margin=0.7, swap=swap)(
            Tensor(a), Tensor(p), Tensor(n))
        exp = torch.nn.TripletMarginLoss(margin=0.7, swap=swap)(
            _t(a), _t(p), _t(n))
        np.testing.assert_allclose(float(got.numpy()), float(exp),
                                   rtol=1e-4)


def test_pairwise_distance_matches_torch():
    import torch
    rng = np.random.RandomState(4)
    x = rng.randn(6, 4).astype(np.float32)
    y = rng.randn(6, 4).astype(np.float32)
    got = nn.PairwiseDistance(p=2.0)(Tensor(x), Tensor(y))
    exp = torch.nn.PairwiseDistance(p=2.0)(_t(x), _t(y))
    np.testing.assert_allclose(np.asarray(got.numpy()), exp.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_square_error_cost():
    x = np.array([1.0, 2.0], np.float32)
    y = np.array([0.5, 4.0], np.float32)
    got = F.square_error_cost(Tensor(x), Tensor(y))
    np.testing.assert_allclose(np.asarray(got.numpy()), [0.25, 4.0],
                               rtol=1e-6)


def test_ctc_loss_matches_torch():
    import torch
    rng = np.random.RandomState(5)
    T, B, C, L = 12, 3, 6, 4
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, L)).astype(np.int32)
    in_lens = np.array([12, 10, 9], np.int64)
    lb_lens = np.array([4, 3, 2], np.int64)
    got = F.ctc_loss(Tensor(logits), Tensor(labels), Tensor(in_lens),
                     Tensor(lb_lens), blank=0, reduction="none")
    tl = torch.nn.functional.ctc_loss(
        torch.log_softmax(_t(logits), dim=-1), _t(labels).long(),
        _t(in_lens), _t(lb_lens), blank=0, reduction="none",
        zero_infinity=False)
    np.testing.assert_allclose(np.asarray(got.numpy()), tl.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_ctc_loss_gradients_flow():
    rng = np.random.RandomState(6)
    T, B, C, L = 8, 2, 5, 3
    logits = Tensor(rng.randn(T, B, C).astype(np.float32))
    logits.stop_gradient = False
    labels = Tensor(rng.randint(1, C, (B, L)).astype(np.int32))
    loss = nn.CTCLoss()(logits, labels,
                        Tensor(np.array([8, 8], np.int64)),
                        Tensor(np.array([3, 2], np.int64)))
    loss.backward()
    g = np.asarray(logits.grad.numpy())
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    # grad rows sum ~0 per (t, b): d/dlogits of a log-softmax-based
    # loss is (p - target-expectation), each row sums to zero
    np.testing.assert_allclose(g.sum(-1), 0.0, atol=1e-5)


def test_ctc_mean_normalises_by_label_length():
    import torch
    rng = np.random.RandomState(7)
    T, B, C, L = 10, 2, 5, 4
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, L)).astype(np.int32)
    in_lens = np.array([10, 10], np.int64)
    lb_lens = np.array([1, 4], np.int64)
    got = F.ctc_loss(Tensor(logits), Tensor(labels), Tensor(in_lens),
                     Tensor(lb_lens), reduction="mean")
    exp = torch.nn.functional.ctc_loss(
        torch.log_softmax(_t(logits), dim=-1), _t(labels).long(),
        _t(in_lens), _t(lb_lens), blank=0, reduction="mean")
    np.testing.assert_allclose(float(got.numpy()), float(exp),
                               rtol=1e-4)


def test_soft_margin_loss_stable_at_large_logits():
    x = np.array([-100.0, 100.0], np.float32)
    y = np.array([1.0, -1.0], np.float32)
    got = np.asarray(F.soft_margin_loss(
        Tensor(x), Tensor(y), reduction="none").numpy())
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, [100.0, 100.0], rtol=1e-5)


def test_poisson_nll_full_grad_finite_at_zero_label():
    x = Tensor(np.array([0.5, -0.2], np.float32))
    x.stop_gradient = False
    y = Tensor(np.array([0.0, 3.0], np.float32))
    loss = F.poisson_nll_loss(x, y, full=True)
    loss.backward()
    assert np.isfinite(np.asarray(x.grad.numpy())).all()


def test_pairwise_distance_p_inf():
    import torch
    x = np.array([[1.0, -4.0, 2.0]], np.float32)
    y = np.array([[0.0, 0.0, 0.0]], np.float32)
    got = F.pairwise_distance(Tensor(x), Tensor(y), p=float("inf"))
    exp = torch.nn.PairwiseDistance(p=float("inf"))(_t(x), _t(y))
    np.testing.assert_allclose(np.asarray(got.numpy()), exp.numpy(),
                               rtol=1e-5)
