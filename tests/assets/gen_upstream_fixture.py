"""Generate byte-accurate REAL-Paddle checkpoint fixtures.

Upstream wire format (paddle/python/paddle/framework/io.py paddle.save):
a single ``pickle.dump(obj, f, protocol=2)`` where every tensor has been
converted to a plain numpy ndarray.

- ``mlp.pdparams``: Layer.state_dict — structured names → ndarray
  (creation order preserved by dict insertion order).
- ``mlp.pdopt``: Adam optimizer state_dict — accumulator keys in the
  upstream ``<internal_param_name>_<slot>_<ordinal>`` grammar
  (``linear_0.w_0_moment1_0`` …), beta-pow accumulators as shape-[1]
  arrays, plus the ``LR_Scheduler`` sub-dict.

Run once to (re)generate the committed binaries:
    python tests/assets/gen_upstream_fixture.py
"""

import os
import pickle

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

IN_F, HID, OUT_F = 4, 8, 2


def params(rng):
    # paddle Linear weight layout: [in_features, out_features]
    return {
        "fc1.weight": rng.randn(IN_F, HID).astype(np.float32) * 0.1,
        "fc1.bias": rng.randn(HID).astype(np.float32) * 0.1,
        "fc2.weight": rng.randn(HID, OUT_F).astype(np.float32) * 0.1,
        "fc2.bias": rng.randn(OUT_F).astype(np.float32) * 0.1,
    }


def opt_state(rng, p):
    # internal (framework-assigned) names in creation order; these never
    # match another process's names — importers must map positionally
    internal = ["linear_0.w_0", "linear_0.b_0",
                "linear_1.w_0", "linear_1.b_0"]
    structured = ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    steps = 3
    sd = {}
    for iname, sname in zip(internal, structured):
        shape = p[sname].shape
        sd[f"{iname}_moment1_0"] = \
            rng.randn(*shape).astype(np.float32) * 0.01
        sd[f"{iname}_moment2_0"] = \
            (rng.rand(*shape).astype(np.float32) * 1e-4)
        sd[f"{iname}_beta1_pow_acc_0"] = \
            np.array([0.9 ** steps], np.float32)
        sd[f"{iname}_beta2_pow_acc_0"] = \
            np.array([0.999 ** steps], np.float32)
    sd["LR_Scheduler"] = {"last_epoch": steps, "last_lr": 0.001}
    return sd


def main():
    rng = np.random.RandomState(20260730)
    p = params(rng)
    with open(os.path.join(HERE, "mlp.pdparams"), "wb") as f:
        pickle.dump(p, f, protocol=2)
    with open(os.path.join(HERE, "mlp.pdopt"), "wb") as f:
        pickle.dump(opt_state(rng, p), f, protocol=2)
    print("wrote mlp.pdparams / mlp.pdopt")


if __name__ == "__main__":
    main()
