"""paddle.inference predictor API (upstream AnalysisPredictor surface,
paddle/fluid/inference/ + python paddle.inference) over the jit.save
StableHLO artifact."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference import (Config, Predictor, create_predictor,
                                  PrecisionType)
from paddle_tpu.static import InputSpec
from paddle_tpu.tensor import Tensor


@pytest.fixture(scope="module")
def saved_model():
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 3))
    net.eval()
    rng = np.random.RandomState(0)
    x = rng.rand(5, 4).astype(np.float32)
    ref = np.asarray(net(Tensor(x)).numpy())
    d = tempfile.mkdtemp()
    path = os.path.join(d, "deploy", "model")
    from paddle_tpu.jit.save_load import save
    # dynamic batch dim — the deployment norm
    save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    return path, x, ref


def test_upstream_handle_workflow(saved_model):
    path, x, ref = saved_model
    config = Config(path + ".pdmodel", path + ".pdiparams")
    predictor = create_predictor(config)

    names = predictor.get_input_names()
    assert len(names) == 1
    h = predictor.get_input_handle(names[0])
    h.reshape([5, 4])
    h.copy_from_cpu(x)
    assert predictor.run() is True

    out_names = predictor.get_output_names()
    out = predictor.get_output_handle(out_names[0])
    got = out.copy_to_cpu()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    assert out.shape() == [5, 3]


def test_dynamic_batch_reruns_other_shape(saved_model):
    path, x, ref = saved_model
    predictor = create_predictor(Config(path))
    h = predictor.get_input_handle("x0")
    x2 = np.concatenate([x, x], axis=0)
    h.copy_from_cpu(x2)
    predictor.run()
    got = predictor.get_output_handle("out0").copy_to_cpu()
    assert got.shape == (10, 3)
    np.testing.assert_allclose(got[:5], ref, rtol=1e-5, atol=1e-6)


def test_list_run_form_and_clone(saved_model):
    path, x, ref = saved_model
    predictor = create_predictor(Config(path))
    outs = predictor.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)
    c = predictor.clone()
    assert c._call is predictor._call           # program shared
    outs2 = c.run([x])
    np.testing.assert_allclose(outs2[0], outs[0], rtol=0, atol=0)


def test_config_model_dir_form(saved_model):
    path, x, ref = saved_model
    config = Config(os.path.dirname(path))      # dir containing one model
    predictor = create_predictor(config)
    outs = predictor.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)


def test_output_handle_stable_across_runs(saved_model):
    """Deployment loops cache output handles at setup; the handle must
    track every run(), not the run it was fetched after."""
    path, x, ref = saved_model
    predictor = create_predictor(Config(path))
    # names and handles are available BEFORE the first run
    assert predictor.get_output_names() == ["out0"]
    out = predictor.get_output_handle("out0")
    h = predictor.get_input_handle("x0")
    h.copy_from_cpu(x)
    predictor.run()
    first = out.copy_to_cpu().copy()
    np.testing.assert_allclose(first, ref, rtol=1e-5, atol=1e-6)
    h.copy_from_cpu(x * 2.0)            # new data, same cached handle
    predictor.run()
    second = out.copy_to_cpu()
    assert not np.allclose(first, second), \
        "cached handle returned stale previous-run data"


def test_run_input_count_mismatch_refuses(saved_model):
    path, x, _ = saved_model
    predictor = create_predictor(Config(path))
    with pytest.raises(ValueError, match="got 2 inputs"):
        predictor.run([x, x])


def test_copy_from_cpu_snapshots_caller_buffer(saved_model):
    """Upstream ZeroCopyTensor copies; mutating the source array after
    copy_from_cpu must not change what run() computes on."""
    path, x, ref = saved_model
    predictor = create_predictor(Config(path))
    buf = x.copy()
    h = predictor.get_input_handle("x0")
    h.copy_from_cpu(buf)
    buf[:] = 0.0                       # caller reuses the staging buffer
    predictor.run()
    got = predictor.get_output_handle("out0").copy_to_cpu()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_set_model_failed_validation_leaves_config_unchanged(saved_model):
    path, _, _ = saved_model
    config = Config(path)
    with pytest.raises(ValueError):
        config.set_model(path + ".pdmodel", "/nonexistent/other.pdiparams")
    assert config.prog_file() == path + ".pdmodel"
    create_predictor(config)           # still loads the original model


def test_set_model_preserves_knobs(saved_model):
    path, _, _ = saved_model
    config = Config(path)
    config.enable_use_gpu(100, 3, PrecisionType.Half)
    config.switch_ir_optim(False)
    config.set_model(path + ".pdmodel", path + ".pdiparams")
    assert config.use_gpu() and config._device_id == 3
    assert config._precision == PrecisionType.Half
    assert not config.ir_optim()
    assert config.prog_file() == path + ".pdmodel"


def test_config_knobs_and_summary(saved_model):
    path, _, _ = saved_model
    config = Config(path)
    config.enable_use_gpu(100, 0, PrecisionType.Half)
    assert config.use_gpu()
    config.disable_gpu()
    assert not config.use_gpu()
    config.switch_ir_optim(False)
    assert not config.ir_optim()
    config.enable_memory_optim()
    s = config.summary()
    assert "model file" in s and path in s
    with pytest.raises(NotImplementedError):
        config.enable_tensorrt_engine(workspace_size=1 << 20)


def test_shape_mismatch_and_unfed_input_refuse(saved_model):
    path, x, _ = saved_model
    predictor = create_predictor(Config(path))
    h = predictor.get_input_handle("x0")
    with pytest.raises(ValueError, match="does not match"):
        h.copy_from_cpu(np.zeros((5, 7), np.float32))
    with pytest.raises(RuntimeError, match="never fed"):
        predictor.run()
    with pytest.raises(KeyError):
        predictor.get_input_handle("nope")


def test_weights_only_artifact_refuses():
    paddle.seed(0)
    net = nn.Linear(2, 2)
    d = tempfile.mkdtemp()
    path = os.path.join(d, "w")
    from paddle_tpu.jit.save_load import save
    save(net, path)        # no input_spec -> no program
    with pytest.raises(RuntimeError, match="no executable program"):
        create_predictor(Config(path))


def test_two_input_model_positional_names():
    paddle.seed(1)

    class Two(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, a, b):
            return self.fc(a) + b

    net = Two()
    net.eval()
    d = tempfile.mkdtemp()
    path = os.path.join(d, "two")
    from paddle_tpu.jit.save_load import save
    save(net, path, input_spec=[InputSpec([2, 4], "float32"),
                                InputSpec([2, 4], "float32")])
    p = create_predictor(Config(path))
    assert p.get_input_names() == ["x0", "x1"]
    rng = np.random.RandomState(3)
    a = rng.rand(2, 4).astype(np.float32)
    b = rng.rand(2, 4).astype(np.float32)
    ref = np.asarray(net(Tensor(a), Tensor(b)).numpy())
    outs = p.run([a, b])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)
