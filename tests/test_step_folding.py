"""Step folding: K train steps fused into one compiled lax.scan
dispatch (ISSUE 5 / DESIGN-PERF.md §Step folding).

Covers the acceptance criteria:
- fold=K end state (params, opt_state, RNG counter, metric results)
  bit-identical to fold=1 on a fixed-seed LeNet run,
- exactly one trace per (signature, fold),
- trailing-partial / uneven-tail groups dispatch scan-of-P over the
  same rolled body (never a numerics-changing fallback),
- callback log_freq / EarlyStopping cadence under folding,
- fold × accumulate_grad_batches composition (in step order),
- device accumulators for Precision/Recall/Auc riding the folded carry,
- the DistributedRunner's deferred wrapper write-back (satellite).
"""

import numpy as np
import pytest

import paddle_tpu as paddle

# retrace sentinel armed module-wide (ISSUE 17): any trace of a
# single-trace compiled entry after its first dispatch raises,
# making every recompile pin in here an ambient property
pytestmark = pytest.mark.usefixtures("retrace_strict")

from paddle_tpu import nn, optimizer
from paddle_tpu.tensor import Tensor


def _mlp():
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))


def _batches(n, bs=8, din=4, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [[rng.rand(bs, din).astype(np.float32),
             rng.randint(0, classes, (bs,)).astype(np.int64)]
            for _ in range(n)]


def _prepared(metrics=None, seed=0, net_fn=_mlp, lr=1e-2):
    paddle.seed(seed)
    m = paddle.Model(net_fn())
    m.prepare(optimizer.Adam(lr, parameters=m.parameters()),
              nn.CrossEntropyLoss(), metrics)
    return m


def _state_of(model):
    sd = {n: np.asarray(v.numpy())
          for n, v in model.network.state_dict().items()}
    opt_state = {
        f"{n}/{k}": np.asarray(v)
        for n, slots in model._train_state.opt_state.items()
        for k, v in slots.items()}
    return sd, opt_state


def _assert_bit_identical(model_a, model_b):
    sd_a, os_a = _state_of(model_a)
    sd_b, os_b = _state_of(model_b)
    assert set(sd_a) == set(sd_b) and set(os_a) == set(os_b)
    for n in sd_a:
        np.testing.assert_array_equal(sd_a[n], sd_b[n],
                                      err_msg=f"param {n} diverged")
    for n in os_a:
        np.testing.assert_array_equal(os_a[n], os_b[n],
                                      err_msg=f"opt state {n} diverged")


# -- bit-identical end-state parity -------------------------------------


def _fit_lenet(fold, batches, epochs=2):
    from paddle_tpu.framework import random as _random
    from paddle_tpu.vision.models import LeNet
    paddle.seed(0)
    acc = paddle.metric.Accuracy()
    m = paddle.Model(LeNet())
    m.prepare(optimizer.Adam(1e-3, parameters=m.parameters()),
              nn.CrossEntropyLoss(), acc)
    m.fit(batches, epochs=epochs, verbose=0, steps_per_dispatch=fold)
    return m, _random.default_generator()._counter, acc.accumulate()


def test_lenet_fold8_bit_identical_to_fold1():
    rng = np.random.RandomState(0)
    batches = [[rng.rand(8, 1, 28, 28).astype(np.float32),
                rng.randint(0, 10, (8,)).astype(np.int64)]
               for _ in range(8)]
    m1, c1, acc1 = _fit_lenet(1, batches)
    m8, c8, acc8 = _fit_lenet(8, batches)
    assert c1 == c8, "RNG counter diverged between fold=1 and fold=8"
    assert acc1 == acc8, "metric result diverged"
    _assert_bit_identical(m1, m8)


def test_mlp_fold_bit_identical_and_counter_aligned():
    from paddle_tpu.framework import random as _random
    batches = _batches(16)

    def run(fold):
        m = _prepared(paddle.metric.Accuracy())
        m.fit(batches, epochs=2, verbose=0, steps_per_dispatch=fold)
        return m, _random.default_generator()._counter

    m1, c1 = run(1)
    m4, c4 = run(4)
    assert c1 == c4
    _assert_bit_identical(m1, m4)


# -- recompile counting --------------------------------------------------


def test_one_trace_per_signature_and_fold():
    m = _prepared(paddle.metric.Accuracy())
    m.fit(_batches(16), epochs=3, verbose=0, steps_per_dispatch=8)
    # 16 batches = two full groups of 8: ONE folded entry, no
    # single-step entry, stable across epochs
    assert m.compile_stats() == {"entries": 1, "traces": 1}
    # a second fold factor compiles exactly one more program
    m.fit(_batches(16), epochs=1, verbose=0, steps_per_dispatch=4)
    assert m.compile_stats() == {"entries": 2, "traces": 2}
    # re-running both stays fully cached
    m.fit(_batches(16), epochs=1, verbose=0, steps_per_dispatch=8)
    m.fit(_batches(16), epochs=1, verbose=0, steps_per_dispatch=4)
    assert m.compile_stats() == {"entries": 2, "traces": 2}


def test_trailing_partial_group_runs_scan_of_p():
    m = _prepared(paddle.metric.Accuracy())
    # 11 batches at fold=4: two scan-of-4 dispatches + one scan-of-3
    m.fit(_batches(11), epochs=1, verbose=0, steps_per_dispatch=4)
    stats = m.compile_stats()
    assert stats == {"entries": 2, "traces": 2}, stats   # fold 4 + 3

    # parity: the mixed 4/4/3 epoch matches a pure fold=1 run — every
    # group executes the same rolled-scan body
    m1 = _prepared(paddle.metric.Accuracy())
    m1.fit(_batches(11), epochs=1, verbose=0, steps_per_dispatch=1)
    _assert_bit_identical(m, m1)


# -- callback cadence ----------------------------------------------------


class _Recorder(paddle.callbacks.Callback):
    def __init__(self):
        super().__init__()
        self.begins = []
        self.ends = []
        self.losses = []
        self.metrics = []

    def on_train_batch_begin(self, step, logs=None):
        self.begins.append(step)

    def on_train_batch_end(self, step, logs=None):
        self.ends.append(step)
        self.losses.append(float(np.asarray(logs["loss"][0])))
        if "acc" in logs:
            self.metrics.append(float(logs["acc"]))


def test_callbacks_fire_per_logical_step_under_folding():
    rec = _Recorder()
    m = _prepared(paddle.metric.Accuracy())
    m.fit(_batches(10), epochs=1, verbose=0, callbacks=[rec],
          steps_per_dispatch=4)
    assert rec.begins == list(range(10))
    assert rec.ends == list(range(10))
    assert all(np.isfinite(v) for v in rec.losses)
    assert len(rec.metrics) == 10
    assert all(0.0 <= v <= 1.0 for v in rec.metrics)

    # the per-step loss values must equal the fold=1 sequence
    rec1 = _Recorder()
    m1 = _prepared(paddle.metric.Accuracy())
    m1.fit(_batches(10), epochs=1, verbose=0, callbacks=[rec1],
           steps_per_dispatch=1)
    np.testing.assert_array_equal(rec.losses, rec1.losses)
    np.testing.assert_array_equal(rec.metrics, rec1.metrics)


def test_early_stopping_under_folding():
    m = _prepared(paddle.metric.Accuracy())
    es = paddle.callbacks.EarlyStopping(monitor="loss", patience=0,
                                        save_best_model=False)
    m.fit(_batches(8), eval_data=_batches(8), epochs=4, verbose=0,
          callbacks=[es], steps_per_dispatch=8)
    assert es.best is not None


def test_progbar_log_freq_formats_folded_values(capsys):
    m = _prepared(paddle.metric.Accuracy())
    m.fit(_batches(8), epochs=1, verbose=2, log_freq=2,
          steps_per_dispatch=4)
    out = capsys.readouterr().out
    assert "step 1/8" in out and "loss:" in out


# -- auto resolution -----------------------------------------------------


def test_auto_fold_resolution():
    # silent run, no callbacks: the AutoFoldTuner calibrates during
    # the first groups and picks K > 1 on this host-bound tiny model,
    # inside the configured bound
    m = _prepared(paddle.metric.Accuracy())
    m.fit(_batches(8), epochs=1, verbose=0)
    assert m._fold_tuner is not None and m._fold_tuner.decided
    assert 1 < m._fold <= m._fold_tuner.max_fold
    assert m._fold == m._fold_tuner.decision["fold"]
    # a verbose progress bar consumes per-step logs: unfolded, no tuner
    m.fit(_batches(4), epochs=1, verbose=2, log_freq=1)
    assert m._fold == 1 and m._fold_tuner is None
    # a user batch hook consumes per-step events: unfolded
    m.fit(_batches(4), epochs=1, verbose=0, callbacks=[_Recorder()])
    assert m._fold == 1 and m._fold_tuner is None
    # explicit request wins over the auto heuristic (no tuner)
    m.fit(_batches(4), epochs=1, verbose=2, steps_per_dispatch=2)
    assert m._fold == 2 and m._fold_tuner is None


def test_host_only_metric_disables_folding():
    class HostMetric(paddle.metric.Metric):
        def __init__(self):
            self.vals = []

        def compute(self, pred, label):
            return Tensor(np.asarray(0.0, np.float32))

        def update(self, x):
            self.vals.append(float(np.asarray(x.numpy())))
            return 0.0

        def reset(self):
            self.vals = []

        def accumulate(self):
            return 0.0

        def name(self):
            return "host"

    m = _prepared(HostMetric())
    with pytest.warns(UserWarning, match="device-side accumulation"):
        m.fit(_batches(8), epochs=1, verbose=0, steps_per_dispatch=8)
    assert m._fold == 0   # legacy per-step entry


# -- fold × accumulate composition --------------------------------------


def test_fold_composes_with_accumulate_grad_batches():
    batches = _batches(16)

    def run(fold):
        m = _prepared(paddle.metric.Accuracy())
        m.fit(batches, epochs=2, verbose=0, accumulate_grad_batches=2,
              steps_per_dispatch=fold)
        return m

    m1 = run(1)
    m4 = run(4)   # 8 logical steps/epoch = two folded groups of 4
    _assert_bit_identical(m1, m4)
    assert m4.compile_stats()["entries"] == 1


# -- device accumulators for Precision / Recall / Auc --------------------


def _binary_batches(n, bs=32, seed=0):
    rng = np.random.RandomState(seed)
    return [[rng.rand(bs, 4).astype(np.float32),
             rng.randint(0, 2, (bs, 1)).astype(np.int64)]
            for _ in range(n)]


def _binary_net():
    # sigmoid head: outputs in (0, 1) as the threshold metrics expect
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1),
                         nn.Sigmoid())


@pytest.mark.parametrize("metric_fn", [
    lambda: paddle.metric.Precision(),
    lambda: paddle.metric.Recall(),
    lambda: paddle.metric.Auc(num_thresholds=255),
])
def test_threshold_metrics_fold_matches_host_path(metric_fn):
    batches = _binary_batches(8)

    # folded run: stats accumulate in the donated scan carry
    paddle.seed(3)
    dev_metric = metric_fn()
    m = paddle.Model(_binary_net())
    m.prepare(optimizer.Adam(1e-2, parameters=m.parameters()),
              nn.BCELoss(), dev_metric)
    m.fit(batches, epochs=1, verbose=0, steps_per_dispatch=8)
    dev_res = dev_metric.accumulate()

    # host reference: an identically-seeded fold-free run feeds every
    # batch's pre-step predictions through the classic numpy update
    paddle.seed(3)
    host_metric = metric_fn()
    ref = paddle.Model(_binary_net())
    ref.prepare(optimizer.Adam(1e-2, parameters=ref.parameters()),
                nn.BCELoss())
    for x, y in batches:
        # train_batch returns no outputs; evaluate the pre-step net
        out = ref.network(Tensor(x))
        host_metric.update(np.asarray(out.numpy()), y)
        ref.train_batch(x, y)
    host_res = host_metric.accumulate()
    np.testing.assert_allclose(dev_res, host_res, rtol=1e-6, atol=1e-9)


def test_accuracy_carry_agrees_with_legacy_pending_path():
    """The folded carry accumulator and the legacy pending-list path
    must produce the same epoch result (counts are exact in float32),
    and fold partitioning must not matter."""
    acc = paddle.metric.Accuracy()
    m = _prepared(acc)
    m.fit(_batches(11), epochs=1, verbose=0, steps_per_dispatch=4)
    r_fold = acc.accumulate()

    acc1 = paddle.metric.Accuracy()
    m1 = _prepared(acc1)
    m1.fit(_batches(11), epochs=1, verbose=0, steps_per_dispatch=1)
    assert r_fold == acc1.accumulate()

    acc0 = paddle.metric.Accuracy()
    m0 = _prepared(acc0)
    m0.fit(_batches(11), epochs=1, verbose=0, steps_per_dispatch=0)
    assert r_fold == acc0.accumulate()


# -- loader integration --------------------------------------------------


def test_fold_through_dataloader():
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.rand(64, 4).astype(np.float32)
            self.y = rng.randint(0, 3, (64,)).astype(np.int64)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    m = _prepared(paddle.metric.Accuracy())
    loader = DataLoader(DS(), batch_size=8, shuffle=False)
    m.fit(loader, epochs=2, verbose=0, steps_per_dispatch=4)
    # the fold hint is reset on fit exit so later unfolded consumers
    # get eager per-batch staging again
    assert loader._fold_hint == 1
    assert m.compile_stats()["entries"] == 1
    for p in m.network.parameters():
        np.asarray(p._value)   # layer tree live after fit


# -- runner deferred wrapper write-back (satellite) ----------------------


def _toy_runner(defer):
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.runner import DistributedRunner
    collective.set_mesh(collective.build_mesh({}))
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    opt = optimizer.Adam(1e-2, parameters=net.parameters())
    r = DistributedRunner(net, opt, nn.CrossEntropyLoss())
    r._defer_wrapper_sync = defer
    return net, r


def test_runner_deferred_write_back_syncs_at_boundary():
    rng = np.random.RandomState(0)
    x = [rng.rand(8, 4).astype(np.float32)]
    y = [rng.randint(0, 3, (8,)).astype(np.int64)]

    net_d, r_d = _toy_runner(defer=True)
    for _ in range(3):
        r_d.train_step(x, y)
    # wrappers are stale (donated) between boundaries by design;
    # sync_to_layers rebinds them to the canonical cached values
    r_d.sync_to_layers()
    got = {n: np.asarray(p._value)
           for n, p in net_d.named_parameters()}

    net_i, r_i = _toy_runner(defer=False)
    for _ in range(3):
        r_i.train_step(x, y)
    want = {n: np.asarray(p._value)
            for n, p in net_i.named_parameters()}
    assert set(got) == set(want)
    for n in got:
        np.testing.assert_array_equal(got[n], want[n])


def test_runner_deferred_adopts_external_write():
    rng = np.random.RandomState(0)
    x = [rng.rand(8, 4).astype(np.float32)]
    y = [rng.randint(0, 3, (8,)).astype(np.int64)]
    net, r = _toy_runner(defer=True)
    loss_a = float(r.train_step(x, y))
    # external in-place write mid-window (checkpoint restore shape):
    # zero one weight wrapper; the next step must consume the zeros
    name, p = next(iter(net.named_parameters()))
    p._value = __import__("jax").numpy.zeros_like(np.zeros(p.shape,
                                                           np.float32))
    r.train_step(x, y)
    r.sync_to_layers()
    # the externally-written leaf trained FROM zero, not from the old
    # weights: its magnitude stays tiny vs the pre-write value
    now = np.abs(np.asarray(dict(net.named_parameters())[name]._value))
    assert float(now.max()) < 0.2, "external write was not adopted"
    assert np.isfinite(loss_a)


def test_model_fit_on_mesh_defers_and_syncs():
    from paddle_tpu.distributed import collective
    collective.set_mesh(collective.build_mesh({}))
    m = _prepared(paddle.metric.Accuracy())
    m.fit(_batches(6), epochs=2, verbose=0)
    assert m._runner is not None
    assert m._runner._defer_wrapper_sync is True
    assert m._runner._wrappers_dirty is False, \
        "fit exit did not flush the deferred wrapper sync"
    w = np.asarray(dict(m.network.named_parameters())["0.weight"]._value)
    assert np.isfinite(w).all()
    # outside fit the public contract returns: train_batch writes back
    x, y = _batches(1)[0]
    m.train_batch(x, y)
    assert m._runner._defer_wrapper_sync is False


# -- review regressions --------------------------------------------------


def test_uneven_trailing_batch_splits_the_group():
    """A dataset whose size is not divisible by batch_size yields a
    smaller final batch (drop_last=False): the fold engine must split
    the group at the shape change instead of np.stack-crashing."""
    m = _prepared(paddle.metric.Accuracy())
    batches = _batches(5) + _batches(1, bs=3, seed=7)
    m.fit(batches, epochs=2, verbose=0, steps_per_dispatch=5)
    # scan-of-5 over the homogeneous prefix + scan-of-1 for the tail,
    # stable across epochs
    assert m.compile_stats() == {"entries": 2, "traces": 2}

    # parity against an unfolded run
    m0 = _prepared(paddle.metric.Accuracy())
    m0.fit(batches, epochs=2, verbose=0, steps_per_dispatch=1)
    _assert_bit_identical(m, m0)


def test_fold_accumulate_callbacks_stay_in_step_order():
    """Accumulate intermediates buffered between folded logical steps
    must replay in order — callbacks see a monotone step series."""
    order = []

    class Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            order.append(step)

    m = _prepared(paddle.metric.Accuracy())
    m.fit(_batches(8), epochs=1, verbose=0, accumulate_grad_batches=2,
          steps_per_dispatch=2, callbacks=[Rec()])
    assert order == list(range(8)), order


def test_runner_invalidate_cache_lets_external_restore_win():
    """invalidate_cache() after a bulk external write (checkpoint
    restore/reshard writes every p._value) must NOT flush the deferred
    wrapper sync over the freshly restored weights."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    x = [rng.rand(8, 4).astype(np.float32)]
    y = [rng.randint(0, 3, (8,)).astype(np.int64)]
    net, r = _toy_runner(defer=True)
    r.train_step(x, y)
    # external restore: overwrite every wrapper, then invalidate
    restored = {n: jnp.zeros(p.shape, jnp.float32)
                for n, p in net.named_parameters()}
    for n, p in net.named_parameters():
        p._value = restored[n]
    r.invalidate_cache()
    for n, p in net.named_parameters():
        assert p._value is restored[n], \
            f"invalidate_cache clobbered the restored value of {n}"
    # training continues from the restored state
    r.train_step(x, y)
    r.sync_to_layers()
    w0 = dict(net.named_parameters())["0.weight"]._value
    assert float(np.abs(np.asarray(w0)).max()) < 0.2, \
        "step did not consume the restore"


def test_by_step_lr_scheduler_forces_fold1():
    """A by-step LR scheduler needs a fresh LR every step; a folded
    dispatch stages one LR for its whole scan.  Explicit
    steps_per_dispatch>1 must warn and degrade to 1 — silently
    training K-1 steps on a stale rate would break the bit-identity
    contract."""
    from paddle_tpu.optimizer import lr as lr_mod
    paddle.seed(0)
    m = paddle.Model(_mlp())
    sched = lr_mod.StepDecay(learning_rate=0.05, step_size=2, gamma=0.5)
    m.prepare(optimizer.SGD(sched, parameters=m.parameters()),
              nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    cb = paddle.callbacks.LRScheduler(by_step=True, by_epoch=False)
    with pytest.warns(UserWarning, match="by-step LR scheduler"):
        m.fit(_batches(8), epochs=1, verbose=0, callbacks=[cb],
              steps_per_dispatch=8)
    assert m._fold == 1

    # and fold=1 really does honor the schedule: end state matches the
    # legacy per-step path driven by the same scheduler
    paddle.seed(0)
    m0 = paddle.Model(_mlp())
    sched0 = lr_mod.StepDecay(learning_rate=0.05, step_size=2,
                              gamma=0.5)
    m0.prepare(optimizer.SGD(sched0, parameters=m0.parameters()),
               nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    m0.fit(_batches(8), epochs=1, verbose=0,
           callbacks=[paddle.callbacks.LRScheduler(by_step=True,
                                                   by_epoch=False)],
           steps_per_dispatch=0)
    sd = {n: np.asarray(v.numpy())
          for n, v in m.network.state_dict().items()}
    sd0 = {n: np.asarray(v.numpy())
           for n, v in m0.network.state_dict().items()}
    for n in sd:
        np.testing.assert_allclose(sd[n], sd0[n], rtol=1e-6,
                                   err_msg=f"param {n} diverged")
