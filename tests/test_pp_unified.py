"""Pipeline engine on the unified dispatcher (ISSUE 15 /
DESIGN-PERF.md §Unified dispatch engine, pp/schedule section).

Covers the acceptance criteria:
- pp end state bit-identical folded vs legacy across K ∈ {1, 3, 8}
  on a CPU pp=2 mesh (the unified scan-of-K and the pre-unification
  per-batch jit compile the one shared schedule body),
- ``Model.fit`` on a pp mesh rides the unified engine
  (``PipelinedRunner``), bit-identical to the direct engine,
- hybrid dp×mp×pp parity through the unified path,
- recompile pin: dispatch 2 of a fixed workload never retraces,
- dispatch-mode / tick-unroll knob resolution.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import collective

# retrace sentinel armed module-wide (ISSUE 17): any trace of a
# single-trace compiled entry after its first dispatch raises,
# making every recompile pin in here an ambient property
pytestmark = pytest.mark.usefixtures("retrace_strict")

from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
    import PipelineParallel


@pytest.fixture(autouse=True)
def _clean_mesh():
    collective.set_mesh(None)
    yield
    collective.set_mesh(None)


def _need_devices(n):
    import jax
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return nn.functional.relu(self.fc(x))


def _make_net(d=16, body=4, stages=2, din=8, classes=5):
    return PipelineLayer(
        [nn.Linear(din, d)] + [Block(d) for _ in range(body)] +
        [nn.Linear(d, classes)],
        num_stages=stages, loss_fn=nn.CrossEntropyLoss())


def _strat(mode=None, accumulate=4):
    class _S:
        pipeline_configs = {"accumulate_steps": accumulate,
                            "micro_batch_size": 2}

    if mode is not None:
        _S.pipeline_configs = dict(_S.pipeline_configs,
                                   dispatch_mode=mode)
    return _S()


def _batches(n=8, bs=8, din=8, classes=5, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.rand(bs, din).astype(np.float32),
             rng.randint(0, classes, (bs,)).astype(np.int64))
            for _ in range(n)]


def _pp_mesh():
    import jax
    return collective.build_mesh({"pp": 2}, devices=jax.devices()[:2])


def _params(net):
    return {n: np.asarray(p._value)
            for n, p in net.named_parameters()}


def _run_legacy(batches):
    paddle.seed(0)
    net = _make_net()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    collective.set_mesh(_pp_mesh())
    eng = PipelineParallel(net, None, _strat("legacy"))
    losses = [float(eng.train_batch((x, y), opt)) for x, y in batches]
    return losses, _params(net)


def _run_folded(batches, K):
    paddle.seed(0)
    net = _make_net()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    collective.set_mesh(_pp_mesh())
    eng = PipelineParallel(net, None, _strat(), optimizer=opt)
    losses = []
    for i in range(0, len(batches), K):
        grp = [([x], [y]) for x, y in batches[i:i + K]]
        ls, _m, _acc = eng.train_steps_folded(grp)
        losses.extend(float(v) for v in ls._materialize()[:len(grp)])
    return losses, _params(net), eng


def test_pp_end_state_folded_vs_legacy_across_K():
    """THE parity anchor: the unified scan-of-K entry and the legacy
    per-batch jit consume the identical key sequence and compile the
    one shared schedule body — end state identical for K ∈ {1, 3, 8},
    trailing partial groups included (8 % 3 != 0).

    In-suite tolerance note: under the suite's
    ``--xla_backend_optimization_level=0`` flag (conftest compile-time
    budget) the CPU backend rounds ONE fused op differently between
    the nested fold-scan program and the single-level legacy program —
    a deterministic 1-ulp artifact of the O0 test flag, bit-exact at
    the production default (pinned by
    ``test_pp_bit_identical_subprocess_default_xla``).  The in-suite
    bound is 2 ulp."""
    _need_devices(2)
    batches = _batches(8)
    ref_losses, ref_params = _run_legacy(batches)
    for K in (1, 3, 8):
        losses, params, _eng = _run_folded(batches, K)
        np.testing.assert_allclose(
            np.asarray(losses), np.asarray(ref_losses),
            rtol=3e-7, atol=0,
            err_msg=f"loss sequence drifted at fold K={K}")
        for n, v in ref_params.items():
            np.testing.assert_allclose(
                params[n], v, rtol=3e-6, atol=3e-7,
                err_msg=f"param {n} drifted at fold K={K}")


def test_pp_bit_identical_subprocess_default_xla():
    """The bit-identity acceptance pin, run under the PRODUCTION XLA
    pipeline (a child process without the suite's O0 compile-budget
    flag): legacy per-batch vs unified fold K ∈ {1, 3, 8} — end state
    and loss sequence EXACTLY equal."""
    _need_devices(2)
    import subprocess
    import sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys; sys.path.insert(0, 'tests'); "
        "sys.path.insert(0, '.')\n"
        "import conftest\n"
        "import numpy as np\n"
        "from test_pp_unified import _batches, _run_legacy, _run_folded\n"
        "from paddle_tpu.distributed import collective\n"
        "batches = _batches(8)\n"
        "ref_losses, ref_params = _run_legacy(batches)\n"
        "collective.set_mesh(None)\n"
        "for K in (1, 3, 8):\n"
        "    losses, params, _e = _run_folded(batches, K)\n"
        "    collective.set_mesh(None)\n"
        "    np.testing.assert_array_equal(np.asarray(losses),\n"
        "                                  np.asarray(ref_losses))\n"
        "    for n, v in ref_params.items():\n"
        "        np.testing.assert_array_equal(params[n], v)\n"
        "print('PP-BIT-IDENTICAL-OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # conftest appends the O0 flag only when absent — pre-setting the
    # production level keeps this child on the real compile pipeline
    env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                        "--xla_backend_optimization_level=2")
    out = subprocess.run([sys.executable, "-c", code], cwd=repo,
                         env=env, capture_output=True, text=True,
                         timeout=480)
    assert out.returncode == 0 and "PP-BIT-IDENTICAL-OK" in out.stdout, \
        (out.stdout[-2000:], out.stderr[-2000:])


def test_pp_unified_train_batch_matches_legacy():
    """The default train_batch entry (unified, scan-of-1) is
    bit-identical to the legacy parity reference."""
    _need_devices(2)
    batches = _batches(6)
    ref_losses, ref_params = _run_legacy(batches)

    paddle.seed(0)
    net = _make_net()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    collective.set_mesh(_pp_mesh())
    eng = PipelineParallel(net, None, _strat())
    assert eng.dispatch_mode == "unified"
    losses = [float(eng.train_batch((x, y), opt)) for x, y in batches]
    np.testing.assert_array_equal(np.asarray(losses),
                                  np.asarray(ref_losses))
    for n, v in ref_params.items():
        np.testing.assert_array_equal(_params(net)[n], v)


def test_pp_recompile_pin():
    """Dispatch 2..N of a fixed workload reuse the compiled programs:
    one fold-cache entry per (fold, shapes) signature, one trace each
    — growth means silent retracing (the PR-11 recompile class)."""
    _need_devices(2)
    batches = _batches(8)
    _losses, _params_, eng = _run_folded(batches, 4)
    stats = eng.compile_stats()
    assert stats["entries"] == 1, stats
    assert stats["traces"] == 1, stats
    # keep dispatching the same signature: still no retrace
    for i in range(0, len(batches), 4):
        grp = [([x], [y]) for x, y in batches[i:i + 4]]
        eng.train_steps_folded(grp)
    stats = eng.compile_stats()
    assert stats["entries"] == 1 and stats["traces"] == 1, stats


def test_pp_recompile_pin_gpt_mp_specs():
    """The verify-drive catch: params carrying mp dist_specs on a mesh
    whose mp axis is size 1 — GSPMD normalizes the trivial axis away
    in its output shardings, so placed specs must canonicalize the
    same way (and the body pins updated params/state back to them) or
    dispatch 2 silently re-lowers the fold program."""
    _need_devices(2)
    from paddle_tpu.models import gpt_tiny, GPTForCausalLMPipe

    cfg = gpt_tiny(use_flash_attention=False)
    rng = np.random.RandomState(0)
    x = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int64)
    y = np.roll(x, -1, axis=1)
    paddle.seed(0)
    net = GPTForCausalLMPipe(cfg, num_stages=2)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    collective.set_mesh(_pp_mesh())
    eng = PipelineParallel(net, None, _strat(), optimizer=opt)
    for _ in range(3):
        eng.train_steps_folded([([x], [y])])
    stats = eng.compile_stats()
    assert stats == {"entries": 1, "traces": 1}, stats


def test_model_fit_pp_mesh_rides_unified_engine():
    """``Model.fit`` on a pp mesh delegates to the pipeline engine
    through the runner interface and its folded dispatches are
    bit-identical to the direct engine sequence."""
    _need_devices(2)
    from paddle_tpu.distributed.runner import PipelinedRunner
    from paddle_tpu.io.dataset import Dataset
    import paddle_tpu.hapi as hapi

    batches = _batches(6)

    class Synth(Dataset):
        def __init__(self):
            self.x = np.concatenate([b[0] for b in batches])
            self.y = np.concatenate([b[1] for b in batches])

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    # reference: direct engine, fold=1 groups (microbatch M=1 — fit's
    # accumulate_grad_batches=1 maps to one microbatch per batch)
    paddle.seed(0)
    ref_net = _make_net()
    ref_opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=ref_net.parameters())
    collective.set_mesh(_pp_mesh())
    ref = PipelineParallel(ref_net, None, _strat(accumulate=1),
                           optimizer=ref_opt)
    for x, y in batches:
        ref.train_steps_folded([([x], [y])])
    ref.sync_to_layers()
    ref_params = _params(ref_net)
    collective.set_mesh(None)

    paddle.seed(0)
    net = _make_net()
    model = hapi.Model(net)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    collective.set_mesh(_pp_mesh())
    model.fit(Synth(), batch_size=8, epochs=1, shuffle=False,
              verbose=0, steps_per_dispatch=2)
    assert isinstance(model._runner, PipelinedRunner), model._runner
    for n, v in ref_params.items():
        # 2-ulp bound for the suite's O0 flag (see the parity anchor's
        # tolerance note); bit-exact under the production pipeline
        np.testing.assert_allclose(
            _params(net)[n], v, rtol=3e-6, atol=3e-7,
            err_msg=f"Model.fit pp end state drifted on {n}")


def test_model_fit_pp_mesh_device_metric():
    """Device metrics ride the folded pp program (in-step stat fns on
    the flat logits, accumulators in the donated carry)."""
    _need_devices(2)
    from paddle_tpu import metric as M
    from paddle_tpu.io.dataset import Dataset
    import paddle_tpu.hapi as hapi

    batches = _batches(4)

    class Synth(Dataset):
        def __init__(self):
            self.x = np.concatenate([b[0] for b in batches])
            self.y = np.concatenate([b[1] for b in batches])

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    paddle.seed(0)
    net = _make_net()
    model = hapi.Model(net)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), M.Accuracy())
    collective.set_mesh(_pp_mesh())
    model.fit(Synth(), batch_size=8, epochs=2, shuffle=False,
              verbose=0, steps_per_dispatch=2)
    acc = model._metrics[0].accumulate()
    assert np.isfinite(acc) and 0.0 <= acc <= 1.0, acc


def test_model_fit_hybrid_dp_mp_pp_through_unified():
    """Hybrid dp×mp×pp through ``Model.fit``: the folded pp program
    composes with dp/mp sharding constraints (the unrolled tick
    schedule on hybrid meshes — the s64/s32 hlo-verifier drift fix)
    and stays bit-identical to the direct engine on the same mesh."""
    _need_devices(8)
    from paddle_tpu.io.dataset import Dataset
    import paddle_tpu.hapi as hapi
    from paddle_tpu.models import gpt_tiny, GPTForCausalLMPipe, \
        GPTPretrainingCriterion

    cfg = gpt_tiny(use_flash_attention=False)
    rng = np.random.RandomState(0)
    x = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int64)
    y = np.roll(x, -1, axis=1)

    def hybrid_mesh():
        return collective.build_mesh({"pp": 2, "dp": 2, "mp": 2})

    # direct engine reference: 2 batches at M=4 microbatches
    paddle.seed(0)
    ref_net = GPTForCausalLMPipe(cfg, num_stages=2)
    ref_opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=ref_net.parameters())
    collective.set_mesh(hybrid_mesh())
    ref = PipelineParallel(ref_net, None, _strat(accumulate=4),
                           optimizer=ref_opt)
    ref_losses = []
    for _ in range(2):
        ls, _m, _acc = ref.train_steps_folded([([x], [y])])
        ref_losses.append(float(ls._materialize()[0]))
    collective.set_mesh(None)

    class Synth(Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return x[i % 8], y[i % 8]

    paddle.seed(0)
    net = GPTForCausalLMPipe(cfg, num_stages=2)
    model = hapi.Model(net)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    model.prepare(opt, GPTPretrainingCriterion(cfg))
    collective.set_mesh(hybrid_mesh())
    # 16 rows / batch 2 = 8 loader batches; accumulate 4 concatenates
    # back to the full 8-row batch = 4 pipeline microbatches → the
    # reference's 2 logical steps, folded into ONE dispatch
    model.fit(Synth(), batch_size=2, epochs=1, shuffle=False,
              verbose=0, accumulate_grad_batches=4,
              steps_per_dispatch=2)
    assert len(ref_losses) == 2 and np.isfinite(ref_losses).all()
    fit_params = _params(net)
    ref.sync_to_layers()
    for n, v in _params(ref_net).items():
        # few-ulp bound for the suite's O0 flag (see the parity
        # anchor's tolerance note; tiny GPT bias elements need the
        # absolute term)
        np.testing.assert_allclose(
            fit_params[n], v, rtol=3e-6, atol=2e-6,
            err_msg=f"hybrid Model.fit drifted on {n}")


def test_pp_dispatch_mode_and_unroll_knobs(monkeypatch):
    _need_devices(2)
    # env wins over config
    monkeypatch.setenv("PADDLE_TPU_PP_DISPATCH", "legacy")
    eng = PipelineParallel(_make_net(), None, _strat("unified"))
    assert eng.dispatch_mode == "legacy"
    monkeypatch.setenv("PADDLE_TPU_PP_DISPATCH", "bogus")
    with pytest.raises(ValueError, match="dispatch_mode"):
        PipelineParallel(_make_net(), None, _strat())
    monkeypatch.delenv("PADDLE_TPU_PP_DISPATCH")

    # tick-loop form: scan on pure pp, unrolled on hybrid meshes
    # (the s64/s32 partitioner workaround), env force wins
    import jax
    eng = PipelineParallel(_make_net(), None, _strat())
    pure = collective.build_mesh({"pp": 2}, devices=jax.devices()[:2])
    assert eng._unroll_ticks(pure) is False
    if len(jax.devices()) >= 4:
        hybrid = collective.build_mesh(
            {"pp": 2, "dp": 2}, devices=jax.devices()[:4])
        assert eng._unroll_ticks(hybrid) is True
    monkeypatch.setenv("PADDLE_TPU_PP_UNROLL_TICKS", "1")
    assert eng._unroll_ticks(pure) is True
    monkeypatch.setenv("PADDLE_TPU_PP_UNROLL_TICKS", "0")
    if len(jax.devices()) >= 4:
        assert eng._unroll_ticks(hybrid) is False
    monkeypatch.delenv("PADDLE_TPU_PP_UNROLL_TICKS")

    # a strategy-exported pipeline_configs knob passes THROUGH the
    # runner adapter (never silently no-ops — the PR-10 review class)
    from paddle_tpu.distributed.runner import PipelinedRunner
    collective.set_mesh(pure)
    net = _make_net()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    r = PipelinedRunner(net, opt, mesh=pure, accumulate_steps=2,
                        pipeline_configs={"dispatch_mode": "legacy",
                                          "unroll_ticks": True,
                                          "remat_stage": False},
                        remat=True)
    assert r._engine.dispatch_mode == "legacy"
    assert r._engine.remat_stage is False      # caller's cfg wins
    assert r._engine._unroll_ticks(pure) is True
    assert r._engine.accumulate_steps == 2     # runner accumulate wins


def test_pp_engine_refuses_multi_input():
    _need_devices(2)
    collective.set_mesh(_pp_mesh())
    net = _make_net()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters())
    eng = PipelineParallel(net, None, _strat(), optimizer=opt)
    x, y = _batches(1)[0]
    with pytest.raises(ValueError, match="one input"):
        eng.train_steps_folded([([x, x], [y])])
